"""Sync-round merge levers (kubeml_tpu/parallel/merge.py).

The contract this file pins, for BOTH engines:

  * bucketed (and fused-apply) merges are BIT-IDENTICAL to the
    monolithic merge — stats lanes on or off, straggler masks, NaN-guard
    fault plans included;
  * error-feedback compressed merges (ef_bf16 / ef_int8) stay within
    quantization tolerance of the f32 merge, keep integer leaves exact,
    and keep EXACT residual bookkeeping: residual == payload - decoded
    per lane, zero on exactly-representable payloads, zeroed for lanes
    the non-finite guard drops and on skipped sync-DP steps;
  * the double-buffered grouped dispatch changes timing only — a job
    warm-started from host numpy buffers (the PR-4 donation-aliasing
    geometry) trains bit-identically with grouping on or off;
  * the comm proxy (bench.py / engine.merge_comm_proxy) is a pure
    function of leaf shapes — exact values pinned here;
  * the merge phase split (merge_wait vs merge_overlap) reaches the
    trace summary and the Prometheus histograms.

tools/check_merge_parity.py lints that every registered strategy stays
covered here.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from kubeml_tpu import compat
from kubeml_tpu.parallel import merge as merge_lib
from kubeml_tpu.parallel.kavg import KAvgEngine
from kubeml_tpu.parallel.mesh import DATA_AXIS

pytestmark = pytest.mark.merge


# --------------------------------------------------------------- fixtures

D_IN, HID = 4, 16


def mlp_loss(variables, batch, rng, sample_mask):
    p = variables["params"]
    h = jnp.tanh(batch["x"] @ p["w1"] + p["b1"])
    pred = (h @ p["w2"] + p["b2"]).squeeze(-1)
    per_ex = (pred - batch["y"]) ** 2
    return per_ex, {}


def mlp_metrics(variables, batch):
    per_ex, _ = mlp_loss(variables, batch, None,
                         jnp.ones(batch["y"].shape[0]))
    return {"loss": per_ex, "accuracy": (per_ex < 1.0).astype(jnp.float32)}


def sgd_factory(lr, epoch):
    return optax.sgd(lr)


def mlp_variables(rng):
    return {"params": {
        "w1": jnp.asarray(rng.randn(D_IN, HID).astype(np.float32) * 0.3),
        "b1": jnp.asarray(rng.randn(HID).astype(np.float32) * 0.1),
        "w2": jnp.asarray(rng.randn(HID, 1).astype(np.float32) * 0.3),
        "b2": jnp.asarray(rng.randn(1).astype(np.float32) * 0.1),
    }}


# a cap of 52 f32 elements: b1(16)+b2(1) pack, w1(64) and w2(16) split —
# several buckets over the tiny MLP so the bucketed path really differs
# structurally from the monolithic one
SMALL_CAP_MB = 52 * 4 / (1024 * 1024)


def round_data(rng, W, S, B):
    xs = rng.randn(W, S, B, D_IN).astype(np.float32)
    ys = rng.randn(W, S, B).astype(np.float32)
    return xs, ys


def assert_trees_equal(a, b, msg=""):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=msg)


def max_tree_diff(a, b):
    return max(float(jnp.max(jnp.abs(
        x.astype(jnp.float32) - y.astype(jnp.float32))))
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b)))


# ---------------------------------------------------------- bucket planner


def test_plan_buckets_cap_and_kind_separation():
    leaves = [jax.ShapeDtypeStruct((30,), jnp.float32),
              jax.ShapeDtypeStruct((30,), jnp.float32),
              jax.ShapeDtypeStruct((), jnp.int32),
              jax.ShapeDtypeStruct((200,), jnp.float32),
              jax.ShapeDtypeStruct((10,), jnp.float32)]
    cap_50 = 50 * 4 / (1024 * 1024)
    plan = merge_lib.plan_buckets(leaves, cap_50)
    # [30], [30] (cap split), [int], [200] (own: larger than cap), [10]
    assert [b.indices for b in plan.buckets] == [
        (0,), (1,), (2,), (3,), (4,)]
    assert [b.compressible for b in plan.buckets] == [
        True, True, False, True, True]
    # uncapped: one bucket per kind run, ints never share with floats
    plan0 = merge_lib.plan_buckets(leaves, 0.0)
    assert [b.indices for b in plan0.buckets] == [(0, 1), (2,), (3, 4)]
    assert plan0.buckets[0].length == 60
    # every leaf appears exactly once, in order
    flat = [i for b in plan0.buckets for i in b.indices]
    assert flat == list(range(len(leaves)))


def test_make_strategy_validation():
    with pytest.raises(ValueError, match="mutually exclusive"):
        merge_lib.make_strategy(merge_dtype=jnp.bfloat16, compress="bf16")
    with pytest.raises(ValueError, match="merge_compress"):
        merge_lib.make_strategy(compress="fp4")
    with pytest.raises(ValueError, match="unknown merge strategy"):
        merge_lib.strategy_by_name("nope")
    # EF without an explicit cap gets the default bucket size
    s = merge_lib.make_strategy(compress="int8")
    assert s.name == "ef_int8" and s.bucket_mb == merge_lib.DEFAULT_EF_BUCKET_MB


# ------------------------------------------------------------ fused kernel


@pytest.mark.parametrize("n", [7, 1024, 5000])
def test_fused_kernel_matches_lax(n):
    """The Pallas merge-apply kernel (interpret mode on CPU) computes
    the same op chain as the lax fallback in both modes — within 1 f32
    ulp (the CPU interpreter may lower the scalar division differently)
    and EXACTLY on the all-dropped guard path, including the pad/reshape
    geometry (n deliberately not a multiple of the 8x128 tile)."""
    from kubeml_tpu.ops.pallas.fused_merge import (fused_avg_select,
                                                   fused_sgd_select)
    rng = np.random.RandomState(n)
    s = jnp.asarray(rng.randn(n).astype(np.float32))
    ref = jnp.asarray(rng.randn(n).astype(np.float32))
    for raw in (0.0, 3.0):
        raw_c = jnp.float32(raw)
        cnt = jnp.maximum(raw_c, 1.0)
        a = fused_avg_select(s, ref, cnt, raw_c, fused=False)
        b = fused_avg_select(s, ref, cnt, raw_c, fused=True,
                             interpret=True)
        g = fused_sgd_select(s, ref, cnt, raw_c, 0.05, fused=False)
        h = fused_sgd_select(s, ref, cnt, raw_c, 0.05, fused=True,
                             interpret=True)
        if raw == 0.0:  # guard-select: both paths must return ref exactly
            np.testing.assert_array_equal(np.asarray(a), np.asarray(ref))
            np.testing.assert_array_equal(np.asarray(b), np.asarray(ref))
            np.testing.assert_array_equal(np.asarray(g), np.asarray(ref))
            np.testing.assert_array_equal(np.asarray(h), np.asarray(ref))
        else:
            # 1-ulp division + FMA-contraction slack; the sgd chain can
            # cancel, so allow a matching absolute floor
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-7, atol=1e-8)
            np.testing.assert_allclose(np.asarray(g), np.asarray(h),
                                       rtol=2e-7, atol=1e-8)


# ------------------------------------------------- kavg engine bit-identity


def _kavg_engine(mesh, collect_stats=True, **merge_kw):
    return KAvgEngine(mesh, mlp_loss, mlp_metrics, sgd_factory,
                      donate=False, collect_stats=collect_stats,
                      **merge_kw)


def _run_kavg_rounds(engine, variables, rounds, fault_plan=None):
    """Dispatch each round, optionally injecting a FaultPlan's NaN
    events through the production host-batch hook."""
    from kubeml_tpu.data.loader import RoundBatch
    losses, dropped = [], []
    for r, (xs, ys, wmask, rngs) in enumerate(rounds):
        W, S, B = xs.shape[:3]
        rb = RoundBatch(batch={"x": xs, "y": ys},
                        sample_mask=np.ones((W, S, B), np.float32),
                        step_mask=np.ones((W, S), np.float32),
                        worker_mask=wmask, rngs=rngs,
                        round_index=r, num_rounds=len(rounds))
        if fault_plan is not None:
            rb = fault_plan.inject_batch(rb)
        variables, stats = engine.train_round(
            variables, {"x": jnp.asarray(rb.batch["x"]),
                        "y": jnp.asarray(rb.batch["y"])},
            sample_mask=rb.sample_mask, step_mask=rb.step_mask,
            worker_mask=rb.worker_mask, rngs=rb.rngs, lr=0.05, epoch=0)
        losses.append(stats.loss_sum)
        dropped.append(stats.dropped)
    return variables, np.stack(losses), np.stack(dropped)


def _make_rounds(rng, n, W=8, S=3, B=4):
    rounds = []
    for r in range(n):
        xs, ys = round_data(rng, W, S, B)
        wmask = np.ones(W, np.float32)
        if r == 1:
            wmask[[2, 5]] = 0.0  # stragglers mid-sweep
        rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)
        rounds.append((xs, ys, wmask, rngs))
    return rounds


@pytest.mark.parametrize("collect_stats", [True, False])
@pytest.mark.parametrize("faulted", [False, True])
def test_kavg_bucketed_bit_identical_to_monolithic(mesh8, collect_stats,
                                                   faulted):
    """The tentpole invariant: splitting the merge into size-capped
    buckets (with the fused-apply path gated off on CPU exactly like
    production) changes NOTHING — weights, losses and guard drops are
    bit-identical to the 'monolithic' per-leaf merge, with stats lanes
    on or off and under a NaN-guard fault plan from faults.py."""
    from kubeml_tpu.faults import FaultPlan
    plan = None
    if faulted:
        plan = FaultPlan.parse([{"kind": "nan", "round": 2, "worker": 3}])
        plan.epoch = 0
    rng = np.random.RandomState(7)
    rounds = _make_rounds(rng, 3)
    v0 = mlp_variables(rng)

    mono = _kavg_engine(mesh8, collect_stats)
    assert mono.merge_strategy == "monolithic"
    vm, lm, dm = _run_kavg_rounds(mono, v0, rounds, plan)

    if plan is not None:
        plan.injected = {k: 0 for k in plan.injected}
    buck = _kavg_engine(mesh8, collect_stats,
                        merge_bucket_mb=SMALL_CAP_MB)
    assert buck.merge_strategy == "bucketed"
    vb, lb, db = _run_kavg_rounds(buck, v0, rounds, plan)

    assert_trees_equal(vm, vb, "bucketed merge diverged from monolithic")
    np.testing.assert_array_equal(lm, lb)
    np.testing.assert_array_equal(dm, db)
    if faulted:
        assert dm[2, 3] == 1.0  # the guard really fired in both engines


def test_kavg_bucketed_int_leaves_exact(mesh8):
    """Integer leaves (BatchNorm counter analogue) ride the exact f32
    wire in every bucketed/compressed strategy — the average-and-
    truncate contract cannot go through a lossy payload."""
    W, S, B = 8, 1, 2
    rng = np.random.RandomState(3)
    xs, ys = round_data(rng, W, S, B)

    def loss_with_counter(variables, batch, rng_, sm):
        per_ex, _ = mlp_loss(variables, batch, rng_, sm)
        return per_ex, {"state": {"count": variables["state"]["count"] + 1}}

    for kw in (dict(merge_bucket_mb=SMALL_CAP_MB),
               dict(merge_compress="bf16"),
               dict(merge_compress="int8")):
        engine = KAvgEngine(mesh8, loss_with_counter, mlp_metrics,
                            sgd_factory, donate=False, **kw)
        variables = {**mlp_variables(np.random.RandomState(0)),
                     "state": {"count": jnp.asarray(1336, jnp.int32)}}
        avg, _ = engine.train_round(
            variables, {"x": jnp.asarray(xs), "y": jnp.asarray(ys)},
            sample_mask=np.ones((W, S, B)), step_mask=np.ones((W, S)),
            worker_mask=np.ones(W), rngs=np.zeros((W, S, 2), np.uint32),
            lr=0.0, epoch=0)
        assert avg["state"]["count"].dtype == jnp.int32
        assert int(avg["state"]["count"]) == 1337, kw


# -------------------------------------------- kavg EF compression + resid


@pytest.mark.parametrize("compress,tol", [("bf16", 2e-2), ("int8", 8e-2)])
def test_kavg_ef_bounded_divergence(mesh8, compress, tol):
    """EF-compressed merges track the f32 merge within quantization
    tolerance over a multi-round trajectory (residual carry working in
    the engine-held state across dispatches) — and really compress."""
    rng = np.random.RandomState(11)
    rounds = _make_rounds(rng, 4)
    v0 = mlp_variables(rng)
    ref, _, _ = _run_kavg_rounds(_kavg_engine(mesh8), v0, rounds)
    eng = _kavg_engine(mesh8, merge_compress=compress)
    assert eng.merge_strategy == f"ef_{compress}"
    out, _, _ = _run_kavg_rounds(eng, v0, rounds)
    for a, b in zip(jax.tree_util.tree_leaves(out),
                    jax.tree_util.tree_leaves(ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=tol, atol=tol)
    assert max_tree_diff(out, ref) > 0.0  # really lossy
    # the residual state persisted and is lane-sharded over the mesh
    assert eng._ef_state and all(
        v.shape[0] % mesh8.shape[DATA_AXIS] == 0
        for v in eng._ef_state.values())


def test_kavg_ef_grouped_rounds_match_sequential(mesh8):
    """EF residuals thread through the multi-round scan carry exactly as
    through per-round dispatches: R grouped rounds == R single rounds,
    bit for bit, including the residual state left behind."""
    rng = np.random.RandomState(13)
    R, W, S, B = 3, 8, 2, 4
    batches = [round_data(rng, W, S, B) for _ in range(R)]
    rngs = rng.randint(0, 2**31, size=(R, W, S, 2)).astype(np.uint32)
    v0 = mlp_variables(rng)
    masks = dict(sample_mask=np.ones((W, S, B), np.float32),
                 step_mask=np.ones((W, S), np.float32),
                 worker_mask=np.ones(W, np.float32))

    seq = _kavg_engine(mesh8, merge_compress="bf16")
    v_seq = v0
    for r in range(R):
        xs, ys = batches[r]
        v_seq, _ = seq.train_round(
            v_seq, {"x": jnp.asarray(xs), "y": jnp.asarray(ys)},
            rngs=rngs[r], lr=0.05, epoch=0, **masks)

    multi = _kavg_engine(mesh8, merge_compress="bf16")
    gmasks = {k: np.broadcast_to(v, (R,) + v.shape).copy()
              for k, v in masks.items()}
    v_multi, _ = multi.train_rounds(
        v0, {"x": jnp.asarray(np.stack([b[0] for b in batches])),
             "y": jnp.asarray(np.stack([b[1] for b in batches]))},
        rngs=rngs, lr=0.05, epoch=0, **gmasks)

    assert_trees_equal(v_seq, v_multi)
    assert set(seq._ef_state) == set(multi._ef_state)
    for k in seq._ef_state:
        np.testing.assert_array_equal(np.asarray(seq._ef_state[k]),
                                      np.asarray(multi._ef_state[k]))


def test_kavg_ef_residual_zeroed_for_dropped_lane(mesh8):
    """Guard semantics survive compression: a NaN-dropped worker's lane
    residual is ZEROED (a revived worker never replays a poisoned or
    stale residual), while surviving lanes keep nonzero cast error."""
    from kubeml_tpu.faults import FaultPlan
    rng = np.random.RandomState(17)
    rounds = _make_rounds(rng, 2)
    plan = FaultPlan.parse([{"kind": "nan", "round": 1, "worker": 3}])
    plan.epoch = 0
    eng = _kavg_engine(mesh8, merge_compress="bf16")
    _run_kavg_rounds(eng, mlp_variables(rng), rounds, plan)
    n_lanes = mesh8.shape[DATA_AXIS]
    for k, v in eng._ef_state.items():
        flat = np.asarray(v)
        L = flat.shape[0] // n_lanes
        np.testing.assert_array_equal(flat[3 * L:4 * L], 0.0,
                                      err_msg=f"{k}: dropped lane residual"
                                              " not zeroed")
        assert np.abs(np.delete(flat.reshape(n_lanes, L), 3, axis=0)
                      ).max() > 0.0


# ------------------------------------- strategy-level residual bookkeeping


def _strategy_lane_merge(mesh, strategy, contribs, alive, residual):
    """Run one strategy.lane_merge under a manual shard_map on the pure
    data mesh: contribs [n_lanes, L] -> (avg [L], residual [n_lanes, L])."""
    n_lanes = mesh.shape[DATA_AXIS]
    L = contribs.shape[1]

    def body(c, al, res):
        c = c.reshape(L)
        lane_alive = al.reshape(())
        raw = lax.psum(jnp.where(lane_alive, 1.0, 0.0), DATA_AXIS)
        cnt = jnp.maximum(raw, 1.0)
        avg, nr = strategy.lane_merge(
            {"w": c}, {"w": jnp.zeros(L, jnp.float32)}, raw, cnt,
            lane_alive=lane_alive, residual={"b0": res.reshape(L)})
        return avg["w"].reshape(1, L), nr["b0"].reshape(1, L)

    f = compat.shard_map(
        jax.jit(body), mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P(DATA_AXIS)), check_vma=False)
    avg, resid = f(jnp.asarray(contribs),
                   jnp.asarray(alive, np.float32).reshape(n_lanes, 1),
                   jnp.asarray(residual))
    return np.asarray(avg)[0], np.asarray(resid)


@pytest.mark.parametrize("name", ["ef_bf16", "ef_int8"])
def test_ef_residual_exact_on_representable_payloads(mesh8, name):
    """On the all-finite greedy path with exactly-representable payloads
    the EF strategies are EXACT: residual comes back all-zero and the
    merged average equals the plain mean bit for bit (int8: payloads are
    integer multiples of the shared scale; bf16: integers small enough
    that every partial sum on the wire stays exactly representable)."""
    strategy = merge_lib.strategy_by_name(name, bucket_mb=4.0)
    n_lanes, L = 8, 32
    rng = np.random.RandomState(5)
    ints = rng.randint(-15, 16, size=(n_lanes, L)).astype(np.float32)
    ints.flat[0] = 127.0  # pin max|p| so the int8 scale is exactly 1.0
    alive = np.ones(n_lanes)
    avg, resid = _strategy_lane_merge(mesh8, strategy, ints, alive,
                                      np.zeros((n_lanes, L), np.float32))
    np.testing.assert_array_equal(resid, 0.0)
    np.testing.assert_array_equal(avg, ints.sum(axis=0) / n_lanes)


@pytest.mark.parametrize("name", ["ef_bf16", "ef_int8"])
def test_ef_dead_lane_residual_zeroed_and_excluded(mesh8, name):
    """A dead lane (quarantined / NaN-dropped) ships zeros, its incoming
    residual is discarded (zeroed, not carried), and the merge equals
    the survivors-only mean exactly."""
    strategy = merge_lib.strategy_by_name(name, bucket_mb=4.0)
    n_lanes, L = 8, 16
    rng = np.random.RandomState(9)
    ints = rng.randint(-15, 16, size=(n_lanes, L)).astype(np.float32)
    ints.flat[1] = 127.0
    alive = np.ones(n_lanes)
    alive[5] = 0.0
    res_in = np.zeros((n_lanes, L), np.float32)
    res_in[5, :] = 3.25  # poisoned-lane leftover that must NOT survive
    avg, resid = _strategy_lane_merge(mesh8, strategy, ints, alive, res_in)
    np.testing.assert_array_equal(resid[5], 0.0)
    expect = ints[alive > 0].sum(axis=0) / np.float32(alive.sum())
    np.testing.assert_array_equal(avg, expect)


def test_ef_residual_is_exact_bookkeeping(mesh8):
    """residual' == payload - decode(payload) per lane, verified against
    a host-side bf16 round-trip of the same payload: the quantization
    error is carried, not approximated."""
    strategy = merge_lib.strategy_by_name("ef_bf16", bucket_mb=4.0)
    n_lanes, L = 8, 24
    rng = np.random.RandomState(21)
    c = rng.randn(n_lanes, L).astype(np.float32)
    res_in = rng.randn(n_lanes, L).astype(np.float32) * 1e-3
    _, resid = _strategy_lane_merge(mesh8, strategy, c,
                                    np.ones(n_lanes), res_in)
    p = c + res_in
    expect = p - p.astype(jnp.bfloat16).astype(np.float32)
    np.testing.assert_array_equal(resid, expect)


# --------------------------------------------------------- sync-DP engine


S_STEPS, B_GLOBAL = 4, 32


def _syncdp_problem(seed=0):
    from kubeml_tpu.models import get_builtin
    model = get_builtin("mlp")(hidden=32, num_classes=4)
    rng = np.random.RandomState(seed)
    centers = rng.randn(4, 16) * 3
    y = rng.randint(0, 4, size=(S_STEPS * 4, B_GLOBAL)).astype(np.int32)
    x = (centers[y] + rng.randn(*y.shape, 16)).astype(np.float32)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x[0])})
    rngs = np.random.RandomState(1).randint(
        0, 2**31, size=(S_STEPS * 4, 2)).astype(np.uint32)
    return model, x, y, variables, rngs


def _run_syncdp(mesh, model, x, y, variables, rngs, strategy,
                nan_at=None, mask_half_at=None, n_rounds=4, **kw):
    from kubeml_tpu.parallel.syncdp import SyncDPEngine
    eng = SyncDPEngine(mesh, model.loss, lambda lr, e: optax.adam(1e-2),
                       donate=False, merge_strategy=strategy, **kw)
    state = eng.init_state(variables)
    for r in range(n_rounds):
        sl = slice(r * S_STEPS, (r + 1) * S_STEPS)
        xs = np.array(x[sl])
        m = np.ones((S_STEPS, B_GLOBAL), np.float32)
        if mask_half_at is not None and r == mask_half_at:
            m[1, B_GLOBAL // 2:] = 0.0
        if nan_at is not None and r == nan_at[0]:
            xs[nan_at[1], :4] = np.nan  # poisons lane 0's shard
        state, losses = eng.train_steps(
            state, {"x": jnp.asarray(xs), "y": jnp.asarray(y[sl])},
            m, rngs[sl], lr=0.0, epoch=0)
    return eng, state


def test_syncdp_explicit_merge_matches_implicit(mesh8):
    """The explicit shard_map merge path ('monolithic' strategy) equals
    the implicit GSPMD all-reduce bit for bit — through straggler masks
    and a NaN skip-step — and the bucketed strategy equals the explicit
    monolithic one the same way."""
    model, x, y, v0, rngs = _syncdp_problem()
    common = dict(nan_at=(2, 1), mask_half_at=1)
    _, base = _run_syncdp(mesh8, model, x, y, v0, rngs, None, **common)
    _, mono = _run_syncdp(mesh8, model, x, y, v0, rngs, "monolithic",
                          **common)
    _, buck = _run_syncdp(mesh8, model, x, y, v0, rngs, "bucketed",
                          merge_bucket_mb=SMALL_CAP_MB, **common)
    assert_trees_equal(base["params"], mono["params"],
                       "explicit monolithic diverged from GSPMD path")
    assert_trees_equal(mono["params"], buck["params"],
                       "bucketed diverged from monolithic")


@pytest.mark.parametrize("strategy,tol", [("ef_bf16", 5e-3),
                                          ("ef_int8", 8e-2)])
def test_syncdp_ef_bounded_divergence(mesh8, strategy, tol):
    model, x, y, v0, rngs = _syncdp_problem()
    _, ref = _run_syncdp(mesh8, model, x, y, v0, rngs, "monolithic")
    eng, out = _run_syncdp(mesh8, model, x, y, v0, rngs, strategy)
    assert max_tree_diff(out["params"], ref["params"]) < tol
    assert "merge_resid" in out
    assert any(float(jnp.abs(v).max()) > 0
               for v in out["merge_resid"].values())


def test_syncdp_skipped_step_zeroes_residual(mesh8):
    """A non-finite global gradient skips the step AND zeroes the EF
    residuals (the poisoned lane's quantization error must not leak into
    the next round's payload). Poisoning the LAST step of a dispatch
    pins the state the round hands back."""
    model, x, y, v0, rngs = _syncdp_problem()
    _, clean = _run_syncdp(mesh8, model, x, y, v0, rngs, "ef_bf16",
                           n_rounds=2)
    assert any(float(jnp.abs(v).max()) > 0
               for v in clean["merge_resid"].values())
    _, out = _run_syncdp(mesh8, model, x, y, v0, rngs, "ef_bf16",
                         nan_at=(1, S_STEPS - 1), n_rounds=2)
    for k, v in out["merge_resid"].items():
        np.testing.assert_array_equal(np.asarray(v), 0.0,
                                      err_msg=f"{k} survived a skip-step")


def test_syncdp_explicit_merge_rejects_fsdp(mesh8):
    from kubeml_tpu.parallel.syncdp import SyncDPEngine
    model, _, _, _, _ = _syncdp_problem()
    with pytest.raises(ValueError, match="fsdp"):
        SyncDPEngine(mesh8, model.loss, lambda lr, e: optax.adam(1e-2),
                     fsdp=True, merge_strategy="bucketed")


# ---------------------------------------------------- comm proxy stability


PROXY_VARS = {"params": {"a": jax.ShapeDtypeStruct((100, 10), jnp.float32),
                         "b": jax.ShapeDtypeStruct((10,), jnp.float32)},
              "state": {"c": jax.ShapeDtypeStruct((), jnp.int32)}}


def test_merge_comm_proxy_exact_values():
    """The comm proxy is a pure function of leaf shapes — these exact
    numbers are the CPU-tier stability contract bench.py reports."""
    assert merge_lib.merge_comm_proxy(PROXY_VARS) == {
        "merge_payload_bytes": 4044, "buckets_per_round": 3,
        "collectives_per_round": 3, "strategy": "monolithic"}
    assert merge_lib.merge_comm_proxy(PROXY_VARS, bucket_mb=4.0) == {
        "merge_payload_bytes": 4044, "buckets_per_round": 2,
        "collectives_per_round": 2, "strategy": "bucketed"}
    assert merge_lib.merge_comm_proxy(PROXY_VARS, compress="bf16") == {
        "merge_payload_bytes": 2024, "buckets_per_round": 2,
        "collectives_per_round": 2, "strategy": "ef_bf16"}
    assert merge_lib.merge_comm_proxy(PROXY_VARS, compress="int8") == {
        "merge_payload_bytes": 1018, "buckets_per_round": 2,
        "collectives_per_round": 2, "strategy": "ef_int8"}
    # bf16 wire cast (legacy knob) halves float bytes, ints stay f32
    assert merge_lib.merge_comm_proxy(
        PROXY_VARS, merge_dtype=jnp.bfloat16)["merge_payload_bytes"] == 2024


def test_bench_comm_proxy_block_stable():
    import bench
    block = bench.comm_proxy_block(PROXY_VARS, rounds_per_epoch=8,
                                   dispatches_per_epoch=3,
                                   programs_compiled=2)
    assert set(block) == set(bench.COMM_PROXY_LEVERS) | {
        "dispatches_per_round", "programs_compiled"}
    assert block["dispatches_per_round"] == 0.375
    assert block["programs_compiled"] == 2
    assert block["monolithic"]["merge_payload_bytes"] == 4044
    assert block["bucketed_4mb"]["buckets_per_round"] == 2
    assert block["ef_bf16"]["merge_payload_bytes"] == 2024
    assert block["ef_int8"]["merge_payload_bytes"] == 1018


def test_engine_comm_proxy_and_program_count(mesh8):
    """Engines expose the proxy + compiled-program count the bench JSON
    records: deterministic before any dispatch, counting after."""
    eng = _kavg_engine(mesh8, merge_compress="bf16")
    proxy = eng.merge_comm_proxy(mlp_variables(np.random.RandomState(0)))
    assert proxy["strategy"] == "ef_bf16"
    assert proxy["merge_payload_bytes"] < 97 * 4  # really compressed
    assert eng.programs_compiled == 0
    rng = np.random.RandomState(1)
    _run_kavg_rounds(eng, mlp_variables(rng), _make_rounds(rng, 1))
    assert eng.programs_compiled == 1


# ------------------------------------------------ options + job wiring


def test_train_options_merge_knobs_round_trip():
    from kubeml_tpu.api.types import TrainOptions
    opts = TrainOptions(merge_dtype="bf16", merge_bucket_mb=2.5)
    d = opts.to_dict()
    assert d["merge_dtype"] == "bf16" and d["merge_bucket_mb"] == 2.5
    assert d["merge_compress"] == "none"
    back = TrainOptions.from_dict(d)
    assert (back.merge_dtype, back.merge_compress, back.merge_bucket_mb) \
        == ("bf16", "none", 2.5)
    # defaults survive an empty dict (old clients)
    old = TrainOptions.from_dict({})
    assert (old.merge_dtype, old.merge_compress, old.merge_bucket_mb) \
        == ("", "none", 0.0)


def test_job_rejects_bad_merge_options(tmp_home, mesh8):
    from tests.test_job import ToyDataset, make_blobs, make_task
    from kubeml_tpu.api.errors import KubeMLException
    from kubeml_tpu.data.registry import DatasetRegistry
    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.train.job import TrainJob

    reg = DatasetRegistry()
    make_blobs(reg)

    def expect_400(mutate, match):
        task = make_task(job_id="mgbad1", epochs=1)
        mutate(task.parameters.options)
        job = TrainJob(task, get_builtin("mlp")(hidden=16, num_classes=4),
                       ToyDataset(), mesh8, registry=reg)
        with pytest.raises(KubeMLException) as ei:
            job.train()
        assert ei.value.status_code == 400
        assert match in str(ei.value.message)

    expect_400(lambda o: setattr(o, "merge_dtype", "fp8"), "merge_dtype")
    expect_400(lambda o: setattr(o, "merge_compress", "zstd"),
               "merge_compress")

    def both(o):
        o.merge_dtype, o.merge_compress = "bf16", "int8"
    expect_400(both, "mutually exclusive")

    def fsdp_bucket(o):
        o.engine, o.fsdp, o.merge_bucket_mb = "syncdp", True, 4.0
    expect_400(fsdp_bucket, "fsdp")

    def sync_dtype(o):
        o.engine, o.merge_dtype = "syncdp", "bf16"
    expect_400(sync_dtype, "kavg")


def test_job_merge_levers_train(tmp_home, mesh8):
    """End-to-end: merge knobs reach the engines through TrainOptions
    and the jobs still converge. Bucketed == plain kavg bit-identically
    (same seeds, same plan); EF-compressed lands close."""
    from tests.test_job import ToyDataset, make_blobs, make_task
    from kubeml_tpu.data.registry import DatasetRegistry
    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.train.checkpoint import load_checkpoint
    from kubeml_tpu.train.job import TrainJob

    reg = DatasetRegistry()
    make_blobs(reg)

    def run(job_id, **opt_kw):
        task = make_task(job_id=job_id, epochs=2, parallelism=3, k=2)
        for k, v in opt_kw.items():
            setattr(task.parameters.options, k, v)
        job = TrainJob(task, get_builtin("mlp")(hidden=16, num_classes=4),
                       ToyDataset(), mesh8, registry=reg)
        rec = job.train()
        variables, _ = load_checkpoint(job_id)
        return rec, variables

    rec0, v0 = run("mglever0")
    rec1, v1 = run("mglever1", merge_bucket_mb=SMALL_CAP_MB)
    assert_trees_equal(v0, v1, "job-level bucketed merge diverged")
    rec2, _ = run("mglever2", merge_compress="int8",
                  merge_bucket_mb=SMALL_CAP_MB)
    np.testing.assert_allclose(rec2.data.train_loss, rec0.data.train_loss,
                               rtol=0.2, atol=0.05)


def test_warm_start_survives_double_buffered_dispatch(tmp_home, mesh8):
    """PR-4 donation-aliasing class, grouped edition: a job warm-started
    from a checkpoint's host numpy buffers enters the double-buffered
    grouped dispatch rotation (two donated buffers in flight). If the
    resume path handed numpy leaves straight to the first donated
    dispatch, the CPU allocator could alias and consume memory the host
    still owns. Geometry + trials follow the elastic regression test;
    grouped and ungrouped warm starts must stay bit-identical."""
    from tests.test_job import ToyDataset, make_blobs, make_task
    from kubeml_tpu.data.registry import DatasetRegistry
    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.train.checkpoint import load_checkpoint
    from kubeml_tpu.train.job import TrainJob

    reg = DatasetRegistry()
    make_blobs(reg, n_train=1024)

    def run(job_id, rpd, resume_from=""):
        task = make_task(job_id=job_id, epochs=2, parallelism=3, k=2)
        task.parameters.options.rounds_per_dispatch = rpd
        task.parameters.resume_from = resume_from
        job = TrainJob(task, get_builtin("mlp")(hidden=16, num_classes=4),
                       ToyDataset(), mesh8, registry=reg)
        job.train()
        return load_checkpoint(job_id)[0]

    run("mgseed", 1)
    for trial in range(3):
        plain = run(f"mgdon_p{trial}", 1, resume_from="mgseed")
        grouped = run(f"mgdon_g{trial}", 2, resume_from="mgseed")
        assert_trees_equal(plain, grouped,
                           f"trial {trial}: warm-started grouped dispatch "
                           "corrupted or diverged")


# ----------------------------------------------------- phase split plumbing


def test_merge_phase_split_in_traces_and_metrics(tmp_path, tmp_home, mesh8):
    """The merge phase splits into merge_wait (blocking drain) and
    merge_overlap (bookkeeping hidden behind the next dispatch): both
    appear in the epoch trace summary of a grouped job, both map to
    Prometheus histograms, and the legacy device_drain key still lands
    in kubeml_job_merge_seconds."""
    from tests.test_job import ToyDataset, make_blobs, make_task
    from kubeml_tpu.api.types import MetricUpdate
    from kubeml_tpu.data.registry import DatasetRegistry
    from kubeml_tpu.metrics.prom import PHASE_HISTOGRAMS, MetricsRegistry
    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.train.job import TrainJob
    from tools.check_metrics import parse_exposition, validate_exposition

    assert PHASE_HISTOGRAMS["merge_wait"] == "merge_seconds"
    assert PHASE_HISTOGRAMS["merge_overlap"] == "merge_overlap_seconds"
    assert PHASE_HISTOGRAMS["device_drain"] == "merge_seconds"  # legacy

    reg = DatasetRegistry()
    make_blobs(reg)
    log = tmp_path / "job.log"
    task = make_task(job_id="mgphase1", epochs=1, parallelism=3, k=2)
    task.parameters.options.rounds_per_dispatch = 2
    job = TrainJob(task, get_builtin("mlp")(hidden=16, num_classes=4),
                   ToyDataset(), mesh8, registry=reg, log_file=str(log))
    job.train()
    text = log.read_text()
    assert re.search(r"merge_overlap=\S+", text)
    assert re.search(r"merge_wait=\S+", text)
    assert "device_drain=" not in text

    mreg = MetricsRegistry()
    mreg.update_job(MetricUpdate(
        job_id="mgphase1", validation_loss=0.5, accuracy=0.9,
        train_loss=0.4, parallelism=3, epoch_duration=1.0,
        phase_times={"merge_wait": [0.05], "merge_overlap": [0.01, 0.02],
                     "device_drain": [0.03]}))
    expo = mreg.exposition()
    assert validate_exposition(expo) == []
    fams = parse_exposition(expo)
    counts = {f: [v for n, _l, v in fams[f]["samples"]
                  if n == f + "_count"][0]
              for f in ("kubeml_job_merge_seconds",
                        "kubeml_job_merge_overlap_seconds")}
    assert counts["kubeml_job_merge_seconds"] == 2  # wait + legacy drain
    assert counts["kubeml_job_merge_overlap_seconds"] == 2


# -------------------------------------------------------- parity lint


def test_check_merge_parity_passes_on_repo():
    import os
    from tools import check_merge_parity as lint
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert lint.main(["check_merge_parity", root]) == 0
    names = lint.registered_strategies(
        os.path.join(root, "kubeml_tpu", "parallel", "merge.py"))
    assert set(names) == {"monolithic", "bucketed", "ef_bf16", "ef_int8"}


def test_check_merge_parity_selftest(tmp_path):
    """The lint catches an uncovered strategy and ignores comment-only
    mentions (self-test mirroring check_fault_tests.py's)."""
    from tools import check_merge_parity as lint
    pkg = tmp_path / "kubeml_tpu" / "parallel"
    pkg.mkdir(parents=True)
    (pkg / "merge.py").write_text(
        '@_register("alpha")\nclass A: pass\n'
        '@_register("beta")\nclass B: pass\n')
    tests = tmp_path / "tests"
    tests.mkdir()
    # alpha: named in code + parity assertion => covered
    (tests / "test_a.py").write_text(
        'def test_a():\n'
        '    s = strategy_by_name("alpha")\n'
        '    np.testing.assert_array_equal(1, 1)\n')
    # beta: only mentioned in a comment => NOT covered
    (tests / "test_b.py").write_text(
        '# "beta" is great\n'
        'def test_b():\n'
        '    np.testing.assert_allclose(1, 1)\n')
    assert lint.uncovered_strategies(str(pkg / "merge.py"),
                                     str(tests)) == ["beta"]
    assert lint.main(["lint", str(tmp_path)]) == 1
    (tests / "test_b.py").write_text(
        'def test_b():\n'
        '    s = strategy_by_name("beta")\n'
        '    np.testing.assert_allclose(1, 1)\n')
    assert lint.main(["lint", str(tmp_path)]) == 0
    # an empty registry means the lint is pointed at the wrong tree
    (pkg / "merge.py").write_text("x = 1\n")
    assert lint.main(["lint", str(tmp_path)]) == 1
