"""Regression tests for code-review findings."""

import numpy as np
import pytest

from kubeml_tpu.api.errors import DataError, InvalidArgsError
from kubeml_tpu.data.loader import RoundLoader
from kubeml_tpu.data.registry import DatasetRegistry
from kubeml_tpu.models.base import KubeDataset
from kubeml_tpu.train.checkpoint import (load_checkpoint, save_checkpoint)


class DS(KubeDataset):
    dataset = "toy"


def test_shuffle_short_doc_no_sample_drop(tmp_path):
    """batch sizes where ceil(52/B)*B < 64 used to drop samples when the
    permutation handed a full doc to a chunk planned for the short doc."""
    reg = DatasetRegistry(str(tmp_path / "ds"))
    rng = np.random.RandomState(0)
    h = reg.create("toy", rng.rand(500, 4).astype(np.float32),
                   rng.randint(0, 2, 500).astype(np.int32),
                   rng.rand(64, 4).astype(np.float32),
                   rng.randint(0, 2, 64).astype(np.int32))
    loader = RoundLoader(h, DS(), n_lanes=2, shuffle=True)
    plan = loader.plan(n_workers=2, k=1, batch_size=13)
    for epoch in range(3):
        seen = sum(int(rb.sample_mask.sum())
                   for rb in loader.epoch_rounds(plan, epoch))
        assert seen == 500, f"epoch {epoch} dropped samples: {seen}"


def test_empty_test_split_clean_error(tmp_path):
    reg = DatasetRegistry(str(tmp_path / "ds"))
    h = reg.create("toy", np.zeros((100, 2), np.float32),
                   np.zeros(100, np.int32),
                   np.zeros((0, 2), np.float32), np.zeros(0, np.int32))
    loader = RoundLoader(h, DS(), n_lanes=2)
    with pytest.raises(DataError):
        loader.eval_batches(2, 16)


@pytest.mark.parametrize("bad", ["../evil", "a/b", "/abs", ".hidden", ""])
def test_path_traversal_names_rejected(tmp_path, bad):
    reg = DatasetRegistry(str(tmp_path / "ds"))
    with pytest.raises(InvalidArgsError):
        reg.exists(bad)


def test_checkpoint_replace_keeps_old_on_overwrite(tmp_path):
    root = str(tmp_path / "models")
    save_checkpoint("j1", {"params": {"w": np.ones(3)}}, {"model": "m"},
                    root=root)
    save_checkpoint("j1", {"params": {"w": np.zeros(3)}}, {"model": "m"},
                    root=root)
    variables, _ = load_checkpoint("j1", root=root)
    np.testing.assert_array_equal(variables["params"]["w"], np.zeros(3))
