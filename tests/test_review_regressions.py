"""Regression tests for code-review findings."""

import numpy as np
import pytest

from kubeml_tpu.api.errors import (DataError, InvalidArgsError,
                                   KubeMLException)
from kubeml_tpu.data.loader import RoundLoader
from kubeml_tpu.data.registry import DatasetRegistry
from kubeml_tpu.models.base import KubeDataset
from kubeml_tpu.train.checkpoint import (load_checkpoint, save_checkpoint)


class DS(KubeDataset):
    dataset = "toy"


def test_shuffle_short_doc_no_sample_drop(tmp_path):
    """batch sizes where ceil(52/B)*B < 64 used to drop samples when the
    permutation handed a full doc to a chunk planned for the short doc."""
    reg = DatasetRegistry(str(tmp_path / "ds"))
    rng = np.random.RandomState(0)
    h = reg.create("toy", rng.rand(500, 4).astype(np.float32),
                   rng.randint(0, 2, 500).astype(np.int32),
                   rng.rand(64, 4).astype(np.float32),
                   rng.randint(0, 2, 64).astype(np.int32))
    loader = RoundLoader(h, DS(), n_lanes=2, shuffle=True)
    plan = loader.plan(n_workers=2, k=1, batch_size=13)
    for epoch in range(3):
        seen = sum(int(rb.sample_mask.sum())
                   for rb in loader.epoch_rounds(plan, epoch))
        assert seen == 500, f"epoch {epoch} dropped samples: {seen}"


def test_empty_test_split_clean_error(tmp_path):
    reg = DatasetRegistry(str(tmp_path / "ds"))
    h = reg.create("toy", np.zeros((100, 2), np.float32),
                   np.zeros(100, np.int32),
                   np.zeros((0, 2), np.float32), np.zeros(0, np.int32))
    loader = RoundLoader(h, DS(), n_lanes=2)
    with pytest.raises(DataError):
        loader.eval_batches(2, 16)


@pytest.mark.parametrize("bad", ["../evil", "a/b", "/abs", ".hidden", ""])
def test_path_traversal_names_rejected(tmp_path, bad):
    reg = DatasetRegistry(str(tmp_path / "ds"))
    with pytest.raises(InvalidArgsError):
        reg.exists(bad)


def test_checkpoint_replace_keeps_old_on_overwrite(tmp_path):
    root = str(tmp_path / "models")
    save_checkpoint("j1", {"params": {"w": np.ones(3)}}, {"model": "m"},
                    root=root)
    save_checkpoint("j1", {"params": {"w": np.zeros(3)}}, {"model": "m"},
                    root=root)
    variables, _ = load_checkpoint("j1", root=root)
    np.testing.assert_array_equal(variables["params"]["w"], np.zeros(3))


# ---------------------------------------------------------------- round-3
# regressions for the round-2 advisor findings


def test_cluster_env_scrub_covers_autodetect_families(monkeypatch):
    """ps._start_standalone scrubs CLUSTER_ENV_VARS from job-child envs;
    that list must cover EVERY family _cluster_env_present (and so
    jobserver's initialize()) auto-detects, or a multi-host serve formed
    from an uncovered family hands the child its parent's rank."""
    from kubeml_tpu.parallel.distributed import (CLUSTER_ENV_VARS,
                                                 _cluster_env_present)
    from kubeml_tpu.control import ps as ps_mod
    assert ps_mod.CLUSTER_ENV_VARS is CLUSTER_ENV_VARS  # one copy, shared

    triggers = {
        "KUBEML_COORDINATOR_ADDRESS": "10.0.0.1:1234",
        "JAX_COORDINATOR_ADDRESS": "10.0.0.1:1234",
        "MEGASCALE_COORDINATOR_ADDRESS": "10.0.0.1:1234",
        "TPU_WORKER_HOSTNAMES": "host-a,host-b",
        "SLURM_NTASKS": "4",
        "OMPI_COMM_WORLD_SIZE": "4",
    }
    for var, value in triggers.items():
        for v in triggers:
            monkeypatch.delenv(v, raising=False)
        monkeypatch.setenv(var, value)
        assert _cluster_env_present(), var
        assert var in CLUSTER_ENV_VARS, \
            f"{var} triggers cluster autodetect but is not scrubbed"
        monkeypatch.delenv(var)


def test_deferred_task_does_not_stall_dispatch(monkeypatch):
    """A 503-deferred task parks with a per-task not-before stamp; tasks
    queued behind it keep dispatching immediately (pre-fix the loop slept
    0.5s inline, degrading ALL dispatch to ~2 attempts/sec)."""
    import threading
    import time as _time

    from kubeml_tpu.api.types import TrainOptions, TrainRequest, TrainTask
    from kubeml_tpu.control import scheduler as sched_mod

    dispatched = {}          # job_id -> [timestamps]
    got_normal = threading.Event()
    lock = threading.Lock()

    def fake_http_json(method, url, body=None, **kwargs):
        jid = body["job_id"]
        with lock:
            dispatched.setdefault(jid, []).append(_time.monotonic())
        if jid == "defer001":
            raise KubeMLException("all device partitions leased", 503)
        got_normal.set()
        return {"ok": True}

    monkeypatch.setattr(sched_mod, "http_json", fake_http_json)
    sched = sched_mod.Scheduler(ps_url="http://fake")
    sched.start()
    try:
        req = TrainRequest(model_type="mlp", batch_size=16, epochs=1,
                           dataset="d", lr=0.1,
                           options=TrainOptions(default_parallelism=1,
                                                static_parallelism=True))
        sched.queue.push(TrainTask(job_id="defer001", parameters=req))
        deadline = _time.monotonic() + 10
        while _time.monotonic() < deadline:
            with lock:
                if dispatched.get("defer001"):
                    break
            _time.sleep(0.01)
        assert dispatched.get("defer001"), "deferred task never attempted"

        t_push = _time.monotonic()
        sched.queue.push(TrainTask(job_id="normal01", parameters=req))
        assert got_normal.wait(5), "normal task never dispatched"
        latency = dispatched["normal01"][0] - t_push
        assert latency < 0.35, \
            f"dispatch stalled {latency:.2f}s behind a deferred task"

        # ... and the deferred task itself retries after its backoff
        deadline = _time.monotonic() + 10
        while _time.monotonic() < deadline:
            with lock:
                if len(dispatched["defer001"]) >= 2:
                    break
            _time.sleep(0.02)
        assert len(dispatched["defer001"]) >= 2, \
            "deferred task was never retried"
    finally:
        sched.stop()
