"""Training-health telemetry (docs/observability.md).

Three layers under test, all on the 8-virtual-CPU-device mesh:

  - the ON-DEVICE STAT LANES: non-perturbation (weights bit-identical
    with stats on or off, both engines), value identities (plain SGD
    makes ``usq == lr^2 * gsq`` exactly), the NaN-select guard (a
    dropped worker's stat rows are zeroed, never NaN-poisoned), and the
    lazy-read discipline (RoundStats.peek() never synchronizes);
  - the HEALTH RULES: fake-clock HealthEvaluator — every rule's onset,
    alert dedup (newly-fired only), window expiry -> unknown;
  - the WIRE + CLI: a deterministic nan fault plan drives a real job
    through the control plane, GET /health?id= goes critical with a
    worker_divergence reason while it runs, and `kubeml top` /
    `kubeml health` render it.
"""

import json
import time
import urllib.request

import jax
import numpy as np
import optax
import pytest

from kubeml_tpu.api.types import TrainOptions, TrainRequest
from kubeml_tpu.control.client import KubemlClient
from kubeml_tpu.control.deployment import start_deployment
from kubeml_tpu.control.health import HealthEvaluator
from kubeml_tpu.control.httpd import http_json
from kubeml_tpu.models import get_builtin
from kubeml_tpu.parallel.kavg import KAvgEngine
from kubeml_tpu.parallel.syncdp import SyncDPEngine
from kubeml_tpu.train.checkpoint import load_checkpoint
from kubeml_tpu.train.job import JobCallbacks, TrainJob

from tests.test_control_plane import write_blob_files
from tests.test_job import ToyDataset, make_blobs, make_task

pytestmark = pytest.mark.health

import jax.numpy as jnp  # noqa: E402

# ------------------------------------------------------- engine-level


def linear_loss(variables, batch, rng, sample_mask):
    w = variables["params"]["w"]
    pred = batch["x"] @ w
    return (pred - batch["y"]) ** 2, {}


def linear_metrics(variables, batch):
    w = variables["params"]["w"]
    pred = batch["x"] @ w
    return {"loss": (pred - batch["y"]) ** 2,
            "accuracy": (jnp.abs(pred - batch["y"]) < 0.5)
            .astype(jnp.float32)}


D = 4
LR = 0.05


def _round_inputs(seed=0, W=8, S=3, B=4, poison_worker=None):
    rng = np.random.RandomState(seed)
    xs = rng.randn(W, S, B, D).astype(np.float32)
    ys = rng.randn(W, S, B).astype(np.float32)
    if poison_worker is not None:
        xs[poison_worker] = np.nan
    w0 = rng.randn(D).astype(np.float32)
    kw = dict(sample_mask=np.ones((W, S, B)), step_mask=np.ones((W, S)),
              worker_mask=np.ones(W), rngs=np.zeros((W, S, 2), np.uint32),
              lr=LR, epoch=0)
    return xs, ys, w0, kw


def _kavg_round(mesh, collect_stats, poison_worker=None):
    xs, ys, w0, kw = _round_inputs(poison_worker=poison_worker)
    engine = KAvgEngine(mesh, linear_loss, linear_metrics,
                        lambda lr, epoch: optax.sgd(lr),
                        collect_stats=collect_stats)
    avg, stats = engine.train_round(
        {"params": {"w": jnp.asarray(w0)}},
        {"x": jnp.asarray(xs), "y": jnp.asarray(ys)}, **kw)
    return avg, stats


def test_kavg_stats_do_not_perturb_weights(mesh8):
    """The non-perturbation guarantee at round granularity: the stat
    lanes are pure extra outputs, so the merged weights are BIT-
    identical with stats on or off."""
    avg_off, stats_off = _kavg_round(mesh8, collect_stats=False)
    avg_on, stats_on = _kavg_round(mesh8, collect_stats=True)
    np.testing.assert_array_equal(np.asarray(avg_off["params"]["w"]),
                                  np.asarray(avg_on["params"]["w"]))
    assert stats_off.stat_device is None
    assert stats_on.stat_device is not None


def test_kavg_stat_lane_values(mesh8):
    """Stat columns carry the real quantities: for plain SGD the update
    is exactly -lr*grad, so usq == lr^2 * gsq per worker; and the
    spread scalar equals the host-computed population std of the
    per-worker mean losses."""
    W, S = 8, 3
    _, stats = _kavg_round(mesh8, collect_stats=True)
    stat = np.asarray(stats.stat_device)
    assert stat.shape == (W, 3)
    gsq, usq, psq = stat[:, 0], stat[:, 1], stat[:, 2]
    assert np.isfinite(stat).all()
    assert (gsq > 0).all() and (psq > 0).all()
    np.testing.assert_allclose(usq, LR ** 2 * gsq, rtol=1e-5)
    worker_means = stats.loss_sum / S
    host_spread = float(np.sqrt(np.mean(worker_means ** 2)
                                - np.mean(worker_means) ** 2))
    np.testing.assert_allclose(float(np.asarray(stats.spread_device)),
                               host_spread, rtol=1e-4)


def test_kavg_nan_worker_stat_rows_zeroed(mesh8):
    """The guard's SELECT (not multiply: NaN*0 == NaN) must also cover
    the stat lanes — a poisoned worker's rows come back zero, and the
    spread is computed over the surviving workers only (finite)."""
    _, stats = _kavg_round(mesh8, collect_stats=True, poison_worker=1)
    dropped = np.asarray(stats.dropped)
    assert dropped[1] == 1.0 and dropped.sum() == 1.0
    stat = np.asarray(stats.stat_device)
    assert np.isfinite(stat).all()
    np.testing.assert_array_equal(stat[1], np.zeros(3))
    keep = np.arange(8) != 1
    assert (stat[keep, 0] > 0).all()
    assert np.isfinite(float(np.asarray(stats.spread_device)))


def test_round_stats_peek_is_non_blocking(mesh8):
    """peek() is the sanctioned mid-epoch look: it returns None (round
    still in flight) or the drained [W] loss sums, and NEVER forces a
    device sync. After the synchronizing loss_sum read it returns the
    same cached array."""
    _, stats = _kavg_round(mesh8, collect_stats=True)
    early = stats.peek()
    assert early is None or isinstance(early, np.ndarray)
    drained = stats.loss_sum  # the synchronizing read
    peeked = stats.peek()
    assert peeked is not None
    np.testing.assert_array_equal(peeked, drained)
    if early is not None:
        np.testing.assert_array_equal(early, drained)


def test_syncdp_stats_do_not_perturb_weights(mesh8):
    """Same guarantee for the sync-DP engine: bit-identical params with
    collect_stats on/off, and the [S, 3] lane obeys the SGD identity."""
    model = get_builtin("mlp")(hidden=16, num_classes=4)
    rng = np.random.RandomState(0)
    S, B, lr = 4, 32, 0.1
    y = rng.randint(0, 4, size=(S, B)).astype(np.int32)
    x = rng.randn(S, B, 8).astype(np.float32)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x[0])})
    rngs = rng.randint(0, 2 ** 31, size=(S, 2)).astype(np.uint32)

    def run(collect_stats):
        eng = SyncDPEngine(mesh8, model.loss,
                           lambda lr_, epoch: optax.sgd(lr_),
                           donate=False, collect_stats=collect_stats)
        state = eng.init_state(variables, lr=lr)
        state, losses = eng.train_steps(
            state, {"x": x, "y": y}, sample_mask=np.ones((S, B)),
            rngs=rngs, lr=lr, epoch=0)
        np.asarray(losses)  # drain the dispatch
        return eng, state

    eng_off, state_off = run(False)
    eng_on, state_on = run(True)
    for a, b in zip(jax.tree_util.tree_leaves(state_off["params"]),
                    jax.tree_util.tree_leaves(state_on["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert eng_off.last_stats_device is None
    stat = np.asarray(eng_on.last_stats_device)
    assert stat.shape == (S, 3)
    assert np.isfinite(stat).all() and (stat[:, 0] > 0).all()
    np.testing.assert_allclose(stat[:, 1], lr ** 2 * stat[:, 0],
                               rtol=1e-4)


# ---------------------------------------------------------- job-level


@pytest.fixture()
def jobenv(tmp_path, tmp_home, mesh8):
    from kubeml_tpu.data.registry import DatasetRegistry
    from kubeml_tpu.train.history import HistoryStore
    reg = DatasetRegistry()
    make_blobs(reg)
    return reg, HistoryStore(), mesh8


def _run_job(jobenv, job_id, engine, train_stats, epochs=2):
    reg, store, mesh = jobenv
    task = make_task(job_id=job_id, epochs=epochs, parallelism=4, k=2,
                     engine=engine)
    task.parameters.options.train_stats = train_stats
    published = []
    job = TrainJob(task, get_builtin("mlp")(hidden=16, num_classes=4),
                   ToyDataset(), mesh, registry=reg, history_store=store,
                   callbacks=JobCallbacks(publish_metrics=published.append))
    record = job.train()
    return record, published


@pytest.mark.parametrize("engine,n_stats",
                         [("kavg", 4), ("syncdp", 1)])
def test_job_weights_bit_identical_stats_on_off(jobenv, engine, n_stats):
    """The acceptance proof: a full fixed-seed job trained with the
    stat lanes on checkpoints the SAME BITS as with them off — for both
    engines — while the on-run publishes real stats (n_stats entries:
    per-worker under kavg, single-model under syncdp) and fills the
    history summaries."""
    rec_on, pub_on = _run_job(jobenv, f"hs-{engine}-on", engine, True)
    rec_off, pub_off = _run_job(jobenv, f"hs-{engine}-off", engine, False)

    v_on, _ = load_checkpoint(f"hs-{engine}-on")
    v_off, _ = load_checkpoint(f"hs-{engine}-off")
    for a, b in zip(jax.tree_util.tree_leaves(v_on),
                    jax.tree_util.tree_leaves(v_off)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    m = pub_on[0]
    assert len(m.grad_norms) == n_stats
    assert all(g > 0 for g in m.grad_norms)
    assert len(m.update_ratios) == n_stats
    assert all(u > 0 for u in m.update_ratios)
    assert len(m.worker_losses) == n_stats
    assert pub_off[0].grad_norms == []
    assert pub_off[0].update_ratios == []

    # runtime introspection rides the same update regardless of stats
    assert m.jit_compiles >= 1
    assert m.hbm_in_use_bytes > 0 and m.hbm_peak_bytes > 0

    # history per-epoch [min, mean, max] summaries (kubeml history list)
    assert len(rec_on.data.grad_norm_summary) == 2
    for lo, mean, hi in rec_on.data.grad_norm_summary:
        assert 0 < lo <= mean <= hi
    for lo, mean, hi in rec_on.data.update_ratio_summary:
        assert 0 < lo <= mean <= hi
    assert rec_off.data.grad_norm_summary == [[0.0, 0.0, 0.0]] * 2


# ----------------------------------------------- fake-clock health rules


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _sample(job_id="j1", **kw):
    """A benign epoch update: no rule fires on these values."""
    base = dict(job_id=job_id, train_loss=0.5, validation_loss=0.5,
                accuracy=50.0, parallelism=2, epoch_duration=1.0,
                dropped_workers=0.0, quarantined_workers=0,
                grad_norms=[0.5, 0.6], update_ratios=[1e-3, 2e-3],
                worker_losses=[0.5, 0.5], loss_spread=0.01,
                phase_times={"dispatch": [0.1, 0.1, 0.1, 0.1]})
    base.update(kw)
    return base


def test_health_benign_updates_are_healthy():
    ev = HealthEvaluator(clock=FakeClock())
    assert ev.observe(_sample()) == []
    v = ev.verdict("j1")
    assert v["state"] == "healthy" and v["reasons"] == []
    assert v["latest"]["grad_norms"] == [0.5, 0.6]


def test_health_unknown_before_any_sample():
    ev = HealthEvaluator(clock=FakeClock())
    assert ev.verdict("ghost")["state"] == "unknown"


@pytest.mark.parametrize("kw,rule,severity", [
    (dict(dropped_workers=1.0), "worker_divergence", "critical"),
    (dict(quarantined_workers=2), "worker_divergence", "critical"),
    (dict(grad_norms=[2e4]), "grad_explosion", "critical"),
    (dict(loss_spread=1.0), "loss_divergence", "warning"),
    (dict(phase_times={"dispatch": [0.1, 0.1, 0.1, 2.0]}),
     "straggler", "warning"),
])
def test_health_single_epoch_rules_fire(kw, rule, severity):
    ev = HealthEvaluator(clock=FakeClock())
    new = ev.observe(_sample(**kw))
    assert [r["rule"] for r in new] == [rule]
    assert new[0]["severity"] == severity
    v = ev.verdict("j1")
    assert v["state"] == ("critical" if severity == "critical"
                          else "warning")


def test_health_grad_explosion_relative_to_window():
    """A 60x jump over the window median fires even below the absolute
    ceiling — divergence has a shape, not just a magnitude."""
    ev = HealthEvaluator(clock=FakeClock())
    assert ev.observe(_sample(grad_norms=[0.5])) == []
    assert ev.observe(_sample(grad_norms=[0.6])) == []
    new = ev.observe(_sample(grad_norms=[30.0]))
    assert [r["rule"] for r in new] == ["grad_explosion"]
    assert "median" in new[0]["detail"]


def test_health_update_stall_needs_consecutive_epochs():
    ev = HealthEvaluator(clock=FakeClock())
    stalled = dict(update_ratios=[1e-9, 1e-9])
    assert ev.observe(_sample(**stalled)) == []
    assert ev.observe(_sample(**stalled)) == []
    new = ev.observe(_sample(**stalled))
    assert [r["rule"] for r in new] == ["update_stall"]
    # one good epoch resets the streak
    assert ev.observe(_sample()) == []
    assert ev.verdict("j1")["state"] == "healthy"


def test_health_alert_dedup_counts_onsets_not_epochs():
    """observe() returns NEWLY-fired reasons only, so the PS alert
    counter measures rule onsets; a rule that clears and re-fires is a
    new onset."""
    ev = HealthEvaluator(clock=FakeClock())
    assert len(ev.observe(_sample(dropped_workers=1.0))) == 1
    assert ev.observe(_sample(dropped_workers=1.0)) == []  # still firing
    assert ev.observe(_sample()) == []                     # cleared
    assert ev.verdict("j1")["state"] == "healthy"
    assert len(ev.observe(_sample(dropped_workers=1.0))) == 1  # re-onset


def test_health_window_expiry_goes_unknown():
    """A job that stops reporting is not healthy — once every sample
    ages out of the rolling window the verdict degrades to unknown."""
    clock = FakeClock()
    ev = HealthEvaluator(clock=clock, window_s=600.0)
    ev.observe(_sample(dropped_workers=1.0))
    assert ev.verdict("j1")["state"] == "critical"
    clock.t += 601.0
    v = ev.verdict("j1")
    assert v["state"] == "unknown" and v["latest"] == {}


def test_health_worst_severity_wins():
    ev = HealthEvaluator(clock=FakeClock())
    new = ev.observe(_sample(dropped_workers=1.0, loss_spread=1.0))
    assert {r["rule"] for r in new} == {"worker_divergence",
                                       "loss_divergence"}
    v = ev.verdict("j1")
    assert v["state"] == "critical"
    # reasons sorted critical-first for the renderer
    assert [r["severity"] for r in v["reasons"]] == ["critical", "warning"]


# -------------------------------------------------------- wire + CLI


@pytest.fixture()
def stack(tmp_path, tmp_home, mesh8):
    dep = start_deployment(mesh=mesh8)
    client = KubemlClient(dep.controller_url)
    yield dep, client, tmp_path
    dep.stop()


def test_health_endpoint_and_top_under_nan_faults(stack, capsys):
    """E2E acceptance: a deterministic fault plan poisons worker 1 every
    round; while the job runs, GET /health?id= serves a critical
    verdict with a worker_divergence reason, the alert counter and the
    one-hot health gauge land on /metrics, and `kubeml top` /
    `kubeml health` render the live document. Finish clears the window:
    the verdict degrades to unknown."""
    from kubeml_tpu.cli.main import main as cli_main

    dep, client, tmp_path = stack
    paths = write_blob_files(tmp_path)
    client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])
    plan = [{"kind": "nan", "worker": 1}]  # every round, every epoch
    req = TrainRequest(
        model_type="mlp", batch_size=32, epochs=50, dataset="blobs",
        lr=0.1, options=TrainOptions(
            default_parallelism=4, static_parallelism=True, k=2,
            fault_plan=json.dumps(plan), device_cache="off"))
    job_id = client.v1().networks().train(req)

    verdict = None
    deadline = time.time() + 120
    while time.time() < deadline:
        doc = http_json("GET", f"{dep.ps.url}/health?id={job_id}")
        if doc["state"] == "critical":
            verdict = doc
            break
        time.sleep(0.1)
    assert verdict is not None, "health never went critical"
    rules = [r["rule"] for r in verdict["reasons"]]
    assert rules.count("worker_divergence") == 1
    assert verdict["latest"]["grad_norms"], "stat lanes missing on wire"

    # bare /health keeps the liveness contract every service answers
    assert http_json("GET", f"{dep.ps.url}/health") == {"ok": True}

    # CLI: machine-readable verdict through the controller proxy...
    cli_main(["--controller", dep.controller_url,
              "health", "--id", job_id])
    doc = json.loads(capsys.readouterr().out)
    assert doc["id"] == job_id and doc["state"] == "critical"

    # ...and the one-shot top render (header, reason, worker table)
    cli_main(["--controller", dep.controller_url, "top", "--id", job_id,
              "--iterations", "1"])
    out = capsys.readouterr().out
    assert f"job {job_id}" in out and "state=critical" in out
    assert "worker_divergence" in out
    assert "WORKER" in out and "GRAD_NORM" in out
    assert "hbm: peak=" in out and "jit compiles:" in out

    # health families on the PS exposition while the job is alive
    text = urllib.request.urlopen(dep.ps.url + "/metrics").read().decode()
    assert ('kubeml_health_alerts_total{jobid="%s",'
            'rule="worker_divergence"}' % job_id) in text
    assert ('kubeml_job_health{jobid="%s",state="critical"} 1'
            % job_id) in text

    client.v1().tasks().stop(job_id)
    assert dep.ps.wait_for_job(job_id, timeout=120)
    # finish clears the rolling window and the gauges: an ended job is
    # unknown, not frozen-healthy
    assert http_json("GET",
                     f"{dep.ps.url}/health?id={job_id}")["state"] \
        == "unknown"
    text = urllib.request.urlopen(dep.ps.url + "/metrics").read().decode()
    assert f'kubeml_job_health{{jobid="{job_id}"' not in text
