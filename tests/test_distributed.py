"""Multi-slice mesh construction + K-avg over a slice-major data axis.

Emulates a 2-slice x 4-chip cluster on the 8 virtual CPU devices
(n_slices forces the contiguous split, since virtual devices carry no
slice_index). Checks the layout contract of
kubeml_tpu/parallel/distributed.py: data axis slice-major, inner axes
confined to a slice, and the unchanged KAvgEngine running end-to-end
over the resulting mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeml_tpu.parallel import distributed
from kubeml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def test_group_by_slice_forced_split():
    devs = jax.devices()
    slices = distributed.group_by_slice(devs, n_slices=2)
    assert [len(s) for s in slices] == [4, 4]
    assert slices[0] == devs[:4] and slices[1] == devs[4:]


def test_group_by_slice_rejects_uneven():
    with pytest.raises(ValueError):
        distributed.group_by_slice(jax.devices(), n_slices=3)


def test_multislice_mesh_slice_major_data_axis():
    mesh = distributed.make_multislice_mesh(n_slices=2)
    assert mesh.shape[DATA_AXIS] == 8
    devs = jax.devices()
    # data lane d = slice * 4 + in-slice lane: first 4 lanes on slice 0
    flat = list(mesh.devices.reshape(8))
    assert flat[:4] == devs[:4] and flat[4:] == devs[4:]


def test_multislice_mesh_inner_axis_within_slice():
    mesh = distributed.make_multislice_mesh(n_model=2, n_slices=2)
    assert mesh.shape[DATA_AXIS] == 4 and mesh.shape[MODEL_AXIS] == 2
    # every model-axis pair must live inside one slice
    devs = jax.devices()
    slice_of = {d: 0 for d in devs[:4]} | {d: 1 for d in devs[4:]}
    grid = mesh.devices.reshape(4, 2)
    for row in grid:
        assert slice_of[row[0]] == slice_of[row[1]]


def test_multislice_mesh_rejects_inner_crossing_slice():
    with pytest.raises(ValueError):
        distributed.make_multislice_mesh(n_model=8, n_slices=2)


def test_kavg_round_over_multislice_mesh():
    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.parallel.kavg import KAvgEngine

    mesh = distributed.make_multislice_mesh(n_slices=2)
    model = get_builtin("lenet")()
    rng = np.random.RandomState(0)
    W, S, B = 8, 2, 4
    x = rng.rand(W, S, B, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, size=(W, S, B)).astype(np.int32)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x[0, 0])})
    engine = KAvgEngine(mesh, model.loss, model.metrics,
                        model.configure_optimizers, donate=False)
    new_vars, stats = engine.train_round(
        variables, {"x": jnp.asarray(x), "y": jnp.asarray(y)},
        sample_mask=np.ones((W, S, B), np.float32),
        step_mask=np.ones((W, S), np.float32),
        worker_mask=np.ones(W, np.float32),
        rngs=rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32),
        lr=0.05, epoch=0)
    assert stats.contributors == W
    # params actually moved
    before = jax.tree_util.tree_leaves(variables["params"])[0]
    after = jax.tree_util.tree_leaves(new_vars["params"])[0]
    assert not np.allclose(np.asarray(before), np.asarray(after))


def test_initialize_single_process_noop():
    # must not raise or hang on a single-process host
    distributed.initialize()
    assert distributed.is_coordinator()


def test_initialize_explicit_args_failure_raises():
    # explicit bring-up must not silently degrade to single-process: here
    # the backend is already initialized, so the join fails immediately
    # and must propagate instead of being swallowed.
    with pytest.raises((RuntimeError, ValueError)):
        distributed.initialize(coordinator_address="127.0.0.1:1",
                               num_processes=2, process_id=1)
