"""Prometheus metric types + exposition round-trip through the lint's
text-format parser (tools/check_metrics.py), which also keeps the lint
itself in the tier-1 suite."""

import math

import pytest

from kubeml_tpu.api.types import MetricUpdate
from kubeml_tpu.metrics.prom import (Counter, Gauge, Histogram, HttpMetrics,
                                     MetricsRegistry)
from tools.check_metrics import (main, parse_exposition, self_test,
                                 validate_exposition)


def test_counter_basics():
    c = Counter("kubeml_demo_total", "help text", ("a", "b"))
    c.inc(("x", "y"))
    c.inc(("x", "y"), 2.0)
    c.inc(("z", "w"))
    assert c.value(("x", "y")) == 3.0
    assert c.value(("missing", "pair")) == 0.0
    with pytest.raises(ValueError):
        c.inc(("x", "y"), -1.0)  # counters only go up
    out = c.collect()
    assert "# TYPE kubeml_demo_total counter" in out
    assert 'kubeml_demo_total{a="x",b="y"} 3.0' in out
    with pytest.raises(ValueError):
        c.inc("onlyone")  # label arity enforced


def test_histogram_cumulative_buckets():
    h = Histogram("kubeml_demo_seconds", "help", ("op",),
                  buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.7, 5.0, 100.0):
        h.observe("x", v)
    out = h.collect()
    assert "# TYPE kubeml_demo_seconds histogram" in out
    # cumulative: ≤0.1 -> 1, ≤1 -> 3, ≤10 -> 4, +Inf -> 5
    assert 'kubeml_demo_seconds_bucket{op="x",le="0.1"} 1' in out
    assert 'kubeml_demo_seconds_bucket{op="x",le="1"} 3' in out
    assert 'kubeml_demo_seconds_bucket{op="x",le="10"} 4' in out
    assert 'kubeml_demo_seconds_bucket{op="x",le="+Inf"} 5' in out
    assert 'kubeml_demo_seconds_count{op="x"} 5' in out
    assert f'kubeml_demo_seconds_sum{{op="x"}} {0.05+0.5+0.7+5.0+100.0}' \
        in out
    h.clear("x")
    assert "_bucket" not in h.collect()


def test_histogram_rejects_bad_bounds():
    with pytest.raises(ValueError):
        Histogram("kubeml_h_seconds", "x", ("a",), buckets=())
    with pytest.raises(ValueError):
        Histogram("kubeml_h_seconds", "x", ("a",), buckets=(1.0, 1.0))
    with pytest.raises(ValueError):
        Histogram("kubeml_h_seconds", "x", ("a",), buckets=(2.0, 1.0))


def test_label_escaping_round_trips():
    g = Gauge("kubeml_esc", "x", "jobid")
    g.set('we"ird\\job\n', 1.0)
    fams = parse_exposition(g.collect())
    (name, labels, value), = fams["kubeml_esc"]["samples"]
    assert labels == {"jobid": 'we"ird\\job\n'}
    assert value == 1.0


def test_restarts_total_is_counter():
    """Satellite fix: the watchdog restart total is monotone and must be
    typed counter (it was exposed as a gauge), while the per-job gauge
    families keep their types for dashboard parity."""
    reg = MetricsRegistry()
    reg.note_restart("jobx")
    expo = reg.exposition()
    assert "# TYPE kubeml_ps_restarts_total counter" in expo
    assert 'kubeml_ps_restarts_total{type="standalone"} 1' in expo
    assert "# TYPE kubeml_job_restarts gauge" in expo  # per-job stays gauge
    assert "# TYPE kubeml_job_running_total gauge" in expo


def test_registry_phase_histograms_and_clear():
    reg = MetricsRegistry()
    reg.update_job(MetricUpdate(
        job_id="jobh", validation_loss=0.5, accuracy=0.9, train_loss=0.4,
        parallelism=8, epoch_duration=1.5,
        phase_times={"dispatch": [0.01, 0.2, 3.0], "data_wait": [0.002],
                     "device_drain": [0.05, 0.06],
                     "epoch": [1.5]}))  # not a phase family: ignored
    expo = reg.exposition()
    fams = parse_exposition(expo)
    for fam, n in (("kubeml_job_dispatch_seconds", 3),
                   ("kubeml_job_data_wait_seconds", 1),
                   ("kubeml_job_merge_seconds", 2)):
        assert fams[fam]["type"] == "histogram"
        counts = [v for name, labels, v in fams[fam]["samples"]
                  if name == fam + "_count"]
        assert counts == [n], fam
    assert validate_exposition(expo) == []
    reg.clear_job("jobh")
    assert 'jobid="jobh"' not in reg.exposition()


def test_http_metrics_exposition():
    m = HttpMetrics("testsvc")
    m.observe("GET", "/metrics", 200, 0.002)
    m.observe("GET", "/metrics", 200, 0.004)
    m.observe("POST", "/update/{jobId}", 404, 0.1)
    expo = m.exposition()
    assert validate_exposition(expo) == []
    fams = parse_exposition(expo)
    reqs = {tuple(sorted(labels.items())): v for _, labels, v
            in fams["kubeml_http_requests_total"]["samples"]}
    assert reqs[(("endpoint", "/metrics"), ("method", "GET"),
                 ("service", "testsvc"), ("status", "200"))] == 2.0
    assert reqs[(("endpoint", "/update/{jobId}"), ("method", "POST"),
                 ("service", "testsvc"), ("status", "404"))] == 1.0


def test_full_exposition_round_trip():
    """The combined PS-style exposition (job families + HTTP middleware
    families) parses clean through the minimal text-format parser and
    survives every lint rule."""
    reg = MetricsRegistry()
    reg.update_job(MetricUpdate(
        job_id="rt1", validation_loss=0.1, accuracy=0.8, train_loss=0.2,
        parallelism=4, epoch_duration=2.0,
        phase_times={"dispatch": [0.01], "data_wait": [0.001],
                     "device_drain": [0.02]}))
    reg.running_total.set("train", 1)
    reg.note_restart("rt1")
    http = HttpMetrics("ps")
    http.observe("GET", "/metrics", 200, 0.001)
    text = reg.exposition() + http.exposition()
    assert validate_exposition(text) == []
    fams = parse_exposition(text)
    # every family present exactly once, all kubeml_-prefixed, and the
    # histogram set the PS serves is at least the three phase families
    # plus HTTP latency
    hist = {f for f, e in fams.items() if e["type"] == "histogram"}
    assert {"kubeml_job_dispatch_seconds", "kubeml_job_data_wait_seconds",
            "kubeml_job_merge_seconds",
            "kubeml_http_request_duration_seconds"} <= hist
    # parser recovers the exact observed value through escaping/formatting
    sums = {labels["jobid"]: v
            for name, labels, v
            in fams["kubeml_job_dispatch_seconds"]["samples"]
            if name.endswith("_sum")}
    assert math.isclose(sums["rt1"], 0.01)


def test_check_metrics_lint():
    # the validator's own self-test: clean exposition accepted, every
    # deliberately broken one flagged
    assert self_test() == []
    # live-registry mode exits clean
    assert main(["check_metrics.py"]) == 0


def test_check_metrics_flags_broken_file(tmp_path):
    bad = tmp_path / "expo.txt"
    bad.write_text("# HELP other_metric x\n# TYPE other_metric gauge\n"
                   "other_metric 1\n")
    assert main(["check_metrics.py", str(bad)]) == 1
    good = tmp_path / "good.txt"
    good.write_text(MetricsRegistry().exposition())
    assert main(["check_metrics.py", str(good)]) == 0
