"""Core-engine tests: K-step local SGD + masked weight averaging.

Verifies the engine against a straight-line numpy re-implementation of the
reference semantics (K local SGD steps per worker from shared weights, then
average weights over contributors — ml/pkg/model/parallelSGD.go:26-54).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubeml_tpu.parallel.kavg import KAvgEngine


def linear_loss(variables, batch, rng, sample_mask):
    w = variables["params"]["w"]
    pred = batch["x"] @ w
    per_ex = (pred - batch["y"]) ** 2
    return per_ex, {}


def linear_metrics(variables, batch):
    w = variables["params"]["w"]
    pred = batch["x"] @ w
    return {"loss": (pred - batch["y"]) ** 2,
            "accuracy": (jnp.abs(pred - batch["y"]) < 0.5).astype(jnp.float32)}


def sgd_factory(lr, epoch):
    return optax.sgd(lr)


D = 4  # feature dim


def make_engine(mesh):
    return KAvgEngine(mesh, linear_loss, linear_metrics, sgd_factory)


def numpy_reference(w0, xs, ys, lr, worker_mask, step_counts):
    """Per-worker local SGD then masked average, in plain numpy."""
    finals = []
    for wi in range(xs.shape[0]):
        w = w0.copy()
        for s in range(step_counts[wi]):
            x, y = xs[wi, s], ys[wi, s]
            grad = 2 * x.T @ (x @ w - y) / x.shape[0]
            w = w - lr * grad
        finals.append(w)
    mask = np.asarray(worker_mask, dtype=float)
    return sum(f * m for f, m in zip(finals, mask)) / mask.sum()


@pytest.fixture(scope="module")
def rng():
    return np.random.RandomState(0)


def make_round_data(rng, W, S, B):
    xs = rng.randn(W, S, B, D).astype(np.float32)
    ys = rng.randn(W, S, B).astype(np.float32)
    return xs, ys


class TestTrainRound:
    def test_matches_numpy_reference_full_masks(self, mesh8, rng):
        W, S, B, lr = 8, 3, 4, 0.05
        xs, ys = make_round_data(rng, W, S, B)
        w0 = rng.randn(D).astype(np.float32)
        engine = make_engine(mesh8)
        variables = {"params": {"w": jnp.asarray(w0)}}
        avg, stats = engine.train_round(
            variables, {"x": jnp.asarray(xs), "y": jnp.asarray(ys)},
            sample_mask=np.ones((W, S, B)), step_mask=np.ones((W, S)),
            worker_mask=np.ones(W), rngs=np.zeros((W, S, 2), np.uint32),
            lr=lr, epoch=0)
        expect = numpy_reference(w0, xs, ys, lr, np.ones(W), [S] * W)
        np.testing.assert_allclose(np.asarray(avg["params"]["w"]), expect,
                                   rtol=1e-5)
        assert stats.contributors == W

    def test_masked_workers_excluded(self, mesh8, rng):
        """Straggler tolerance: only contributors enter the average
        (parity: merge-with-whoever-reported, ml/pkg/train/job.go:388-398)."""
        W, S, B, lr = 8, 2, 4, 0.1
        xs, ys = make_round_data(rng, W, S, B)
        w0 = rng.randn(D).astype(np.float32)
        worker_mask = np.array([1, 1, 1, 0, 1, 0, 1, 1], dtype=float)
        engine = make_engine(mesh8)
        avg, stats = engine.train_round(
            {"params": {"w": jnp.asarray(w0)}},
            {"x": jnp.asarray(xs), "y": jnp.asarray(ys)},
            sample_mask=np.ones((W, S, B)), step_mask=np.ones((W, S)),
            worker_mask=worker_mask, rngs=np.zeros((W, S, 2), np.uint32),
            lr=lr, epoch=0)
        expect = numpy_reference(w0, xs, ys, lr, worker_mask, [S] * W)
        np.testing.assert_allclose(np.asarray(avg["params"]["w"]), expect,
                                   rtol=1e-5)
        assert stats.contributors == 6

    def test_compressed_merge_close_to_f32(self, mesh8, rng):
        """merge_dtype=bf16 halves the all-reduce bytes; the result must
        stay within bf16 relative error of the f32 merge, including with
        masked (straggler) workers."""
        W, S, B, lr = 8, 3, 4, 0.05
        xs, ys = make_round_data(rng, W, S, B)
        w0 = rng.randn(D).astype(np.float32)
        worker_mask = np.array([1, 1, 0, 1, 1, 1, 0, 1], dtype=float)
        kw = dict(sample_mask=np.ones((W, S, B)), step_mask=np.ones((W, S)),
                  worker_mask=worker_mask,
                  rngs=np.zeros((W, S, 2), np.uint32), lr=lr, epoch=0)
        batch = {"x": jnp.asarray(xs), "y": jnp.asarray(ys)}
        variables = {"params": {"w": jnp.asarray(w0)}}

        ref_eng = KAvgEngine(mesh8, linear_loss, linear_metrics, sgd_factory,
                             donate=False)
        ref, _ = ref_eng.train_round(variables, batch, **kw)
        eng = KAvgEngine(mesh8, linear_loss, linear_metrics, sgd_factory,
                         donate=False, merge_dtype=jnp.bfloat16)
        out, stats = eng.train_round(variables, batch, **kw)
        assert stats.contributors == 6
        a, b = np.asarray(out["params"]["w"]), np.asarray(ref["params"]["w"])
        np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-2)
        assert not np.allclose(a, b, rtol=1e-6, atol=0)  # really compressed

        # the compressed engine still trains: a few rounds reduce loss
        var, first = variables, None
        for r in range(5):
            var, st = eng.train_round(var, batch, **kw)
            loss = float(st.loss_sum.sum())
            first = loss if first is None else first
        assert loss < first

    def test_compressed_merge_on_mixed_mesh(self, mesh4x2, rng):
        """Compression now composes with DP x TP meshes (the r1 box):
        the merge rides the bf16 ppermute ring (collectives.py) because
        a partially-manual sub-f32 psum fatally miscompiles. Result must
        match the pure-f32 merge on the same mesh to bf16 tolerance."""
        W, S, B, lr = 8, 3, 4, 0.05
        xs, ys = make_round_data(rng, W, S, B)
        w0 = rng.randn(D).astype(np.float32)
        worker_mask = np.array([1, 1, 0, 1, 1, 1, 0, 1], dtype=float)
        kw = dict(sample_mask=np.ones((W, S, B)),
                  step_mask=np.ones((W, S)), worker_mask=worker_mask,
                  rngs=np.zeros((W, S, 2), np.uint32), lr=lr, epoch=0)
        batch = {"x": jnp.asarray(xs), "y": jnp.asarray(ys)}
        variables = {"params": {"w": jnp.asarray(w0)}}

        ref_eng = KAvgEngine(mesh4x2, linear_loss, linear_metrics,
                             sgd_factory, donate=False)
        ref, _ = ref_eng.train_round(variables, batch, **kw)
        eng = KAvgEngine(mesh4x2, linear_loss, linear_metrics,
                         sgd_factory, donate=False,
                         merge_dtype=jnp.bfloat16)
        assert eng._compressed_ring
        out, stats = eng.train_round(variables, batch, **kw)
        assert stats.contributors == 6
        a = np.asarray(out["params"]["w"])
        b = np.asarray(ref["params"]["w"])
        np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-2)

    def test_compressed_merge_rejects_bad_configs(self, mesh4x2, rng):
        """Non-float wire dtypes fail loudly. (Round 2 also rejected
        compression x seq-parallel training here; round 3's fully-manual
        rounds carry it — tests/test_manual_tp.py pins that path.)"""
        with pytest.raises(ValueError, match="floating"):
            KAvgEngine(mesh4x2, linear_loss, linear_metrics, sgd_factory,
                       donate=False, merge_dtype=jnp.int16)
        from kubeml_tpu.parallel.mesh import make_mesh
        seq_mesh = make_mesh(n_data=2, n_seq=2)
        eng = KAvgEngine(seq_mesh, linear_loss, linear_metrics,
                         sgd_factory, donate=False,
                         merge_dtype=jnp.bfloat16, batch_seq_dims={"x": 0})
        assert eng._full_manual and not eng._compressed_ring

    def test_step_mask_freezes_padded_steps(self, mesh8, rng):
        """Ragged chunks: a masked step must leave weights untouched."""
        W, S, B, lr = 8, 3, 4, 0.05
        xs, ys = make_round_data(rng, W, S, B)
        w0 = rng.randn(D).astype(np.float32)
        step_counts = [3, 2, 1, 3, 2, 1, 3, 2]
        step_mask = np.zeros((W, S))
        for i, c in enumerate(step_counts):
            step_mask[i, :c] = 1
        engine = make_engine(mesh8)
        avg, _ = engine.train_round(
            {"params": {"w": jnp.asarray(w0)}},
            {"x": jnp.asarray(xs), "y": jnp.asarray(ys)},
            sample_mask=np.ones((W, S, B)), step_mask=step_mask,
            worker_mask=np.ones(W), rngs=np.zeros((W, S, 2), np.uint32),
            lr=lr, epoch=0)
        expect = numpy_reference(w0, xs, ys, lr, np.ones(W), step_counts)
        np.testing.assert_allclose(np.asarray(avg["params"]["w"]), expect,
                                   rtol=1e-5)

    def test_sample_mask_partial_batch(self, mesh8, rng):
        """A partial final batch averages loss over real samples only."""
        W, S, B, lr = 8, 1, 4, 0.05
        xs, ys = make_round_data(rng, W, S, B)
        w0 = rng.randn(D).astype(np.float32)
        sample_mask = np.ones((W, S, B))
        sample_mask[:, :, 2:] = 0  # only 2 real samples per batch
        engine = make_engine(mesh8)
        avg, _ = engine.train_round(
            {"params": {"w": jnp.asarray(w0)}},
            {"x": jnp.asarray(xs), "y": jnp.asarray(ys)},
            sample_mask=sample_mask, step_mask=np.ones((W, S)),
            worker_mask=np.ones(W), rngs=np.zeros((W, S, 2), np.uint32),
            lr=lr, epoch=0)
        # numpy reference with truncated batches
        expect = numpy_reference(w0, xs[:, :, :2], ys[:, :, :2], lr,
                                 np.ones(W), [S] * W)
        np.testing.assert_allclose(np.asarray(avg["params"]["w"]), expect,
                                   rtol=1e-5)

    def test_virtual_workers_more_than_lanes(self, mesh8, rng):
        """W=16 logical workers on 8 lanes: identical result to the math."""
        W, S, B, lr = 16, 2, 4, 0.05
        xs, ys = make_round_data(rng, W, S, B)
        w0 = rng.randn(D).astype(np.float32)
        engine = make_engine(mesh8)
        avg, _ = engine.train_round(
            {"params": {"w": jnp.asarray(w0)}},
            {"x": jnp.asarray(xs), "y": jnp.asarray(ys)},
            sample_mask=np.ones((W, S, B)), step_mask=np.ones((W, S)),
            worker_mask=np.ones(W), rngs=np.zeros((W, S, 2), np.uint32),
            lr=lr, epoch=0)
        expect = numpy_reference(w0, xs, ys, lr, np.ones(W), [S] * W)
        np.testing.assert_allclose(np.asarray(avg["params"]["w"]), expect,
                                   rtol=1e-5)

    def test_integer_leaves_averaged_with_trunc(self, mesh8, rng):
        """int leaves (BatchNorm num_batches_tracked analogue) survive the
        average with dtype preserved (parallelSGD.go:40-52 parity)."""
        W, S, B = 8, 1, 2
        xs, ys = make_round_data(rng, W, S, B)

        def loss_with_counter(variables, batch, rng_, sm):
            per_ex, _ = linear_loss(variables, batch, rng_, sm)
            return per_ex, {"state": {"count": variables["state"]["count"] + 1}}

        # int leaves must stay EXACT in both merge modes — bf16 compression
        # skips non-float leaves (a 7-bit mantissa would drift counters)
        for merge_dtype, start, want in ((None, 7, 8), (jnp.bfloat16, 7, 8),
                                         (jnp.bfloat16, 1336, 1337)):
            engine = KAvgEngine(mesh8, loss_with_counter, linear_metrics,
                                sgd_factory, donate=False,
                                merge_dtype=merge_dtype)
            variables = {"params": {"w": jnp.zeros(D, jnp.float32)},
                         "state": {"count": jnp.asarray(start, jnp.int32)}}
            avg, _ = engine.train_round(
                variables, {"x": jnp.asarray(xs), "y": jnp.asarray(ys)},
                sample_mask=np.ones((W, S, B)), step_mask=np.ones((W, S)),
                worker_mask=np.ones(W), rngs=np.zeros((W, S, 2), np.uint32),
                lr=0.0, epoch=0)
            assert avg["state"]["count"].dtype == jnp.int32
            assert int(avg["state"]["count"]) == want, merge_dtype


class TestEvalRound:
    def test_weighted_metrics(self, mesh8, rng):
        W, S, B = 8, 2, 4
        xs, ys = make_round_data(rng, W, S, B)
        w0 = rng.randn(D).astype(np.float32)
        sample_mask = np.ones((W, S, B))
        sample_mask[0, 1, :] = 0  # drop one whole step
        engine = make_engine(mesh8)
        out = engine.eval_round(
            {"params": {"w": jnp.asarray(w0)}},
            {"x": jnp.asarray(xs), "y": jnp.asarray(ys)}, sample_mask)
        pred = np.einsum("wsbd,d->wsb", xs, w0)
        per_ex = (pred - ys) ** 2
        n = sample_mask.sum()
        np.testing.assert_allclose(out["loss"],
                                   (per_ex * sample_mask).sum() / n, rtol=1e-5)
        assert out["n"] == n


def test_train_rounds_matches_sequential_single_rounds(mesh8, rng):
    """R rounds in ONE dispatch (train_rounds) computes exactly the
    same averaged weights and per-round losses as R single-round
    dispatches — the multi-round program exists only to cut dispatch
    overhead (experiments/round_probe.py), never to change math."""
    W, S, B, R = 8, 3, 4, 3
    w0 = rng.randn(D).astype(np.float32)
    batches = [make_round_data(rng, W, S, B) for _ in range(R)]
    rngs = rng.randint(0, 2**31, size=(R, W, S, 2)).astype(np.uint32)
    masks = np.ones((R, W, S, B), np.float32)
    smask = np.ones((R, W, S), np.float32)
    # round 1 masks out two workers; round 2 a ragged step — the stats
    # and merges must stay per-round exact
    wmask = np.ones((R, W), np.float32)
    wmask[1, :2] = 0.0
    smask[2, 3, -1] = 0.0

    seq = KAvgEngine(mesh8, linear_loss, linear_metrics, sgd_factory,
                     donate=False)
    v_seq = {"params": {"w": jnp.asarray(w0)}}
    seq_losses = []
    for r in range(R):
        xs, ys = batches[r]
        v_seq, stats = seq.train_round(
            v_seq, {"x": jnp.asarray(xs), "y": jnp.asarray(ys)},
            sample_mask=masks[r], step_mask=smask[r],
            worker_mask=wmask[r], rngs=rngs[r], lr=0.05, epoch=0)
        seq_losses.append(stats.loss_sum)

    multi = KAvgEngine(mesh8, linear_loss, linear_metrics, sgd_factory,
                       donate=False)
    xs_all = np.stack([b[0] for b in batches])
    ys_all = np.stack([b[1] for b in batches])
    v_multi, mstats = multi.train_rounds(
        {"params": {"w": jnp.asarray(w0)}},
        {"x": jnp.asarray(xs_all), "y": jnp.asarray(ys_all)},
        sample_mask=masks, step_mask=smask, worker_mask=wmask,
        rngs=rngs, lr=0.05, epoch=0)

    np.testing.assert_allclose(np.asarray(v_multi["params"]["w"]),
                               np.asarray(v_seq["params"]["w"]),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(mstats.loss_sum_device),
                               np.stack(seq_losses), rtol=1e-5, atol=1e-5)
    assert mstats.step_count.shape == (R, W)
    # one compiled program regardless of R repeats
    v2, st2 = multi.train_rounds(
        v_multi, {"x": jnp.asarray(xs_all), "y": jnp.asarray(ys_all)},
        sample_mask=masks, step_mask=smask, worker_mask=wmask,
        rngs=rngs, lr=0.05, epoch=0)
    assert not st2.compiled
