"""The examples/ function files: registry resolution + an end-to-end
train of the LeNet example through the control plane."""

import os
import time

import numpy as np
import pytest

from kubeml_tpu.train.functionlib import FunctionRegistry

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


@pytest.mark.parametrize("fname,fn_name", [
    ("function_lenet.py", "lenet-example"),
    ("function_resnet34.py", "resnet34-example"),
    ("function_vgg11.py", "vgg11-example"),
])
def test_example_resolves(tmp_home, fname, fn_name):
    reg = FunctionRegistry()
    reg.create(fn_name, os.path.join(EXAMPLES, fname))
    model_cls, dataset_cls = reg.resolve(fn_name)
    model = model_cls()
    assert model.num_classes >= 10
    assert dataset_cls is not None
    ds = dataset_cls()
    out = ds.transform_train(np.random.rand(4, 32, 32, 3).astype(np.float32)
                             if "lenet" not in fname else
                             np.random.rand(4, 28, 28).astype(np.float32),
                             np.zeros(4, np.int64))
    assert set(out) == {"x", "y"} and out["x"].dtype == np.float32


def test_lenet_example_trains_end_to_end(tmp_home, tmp_path, mesh8):
    from kubeml_tpu.api.types import TrainOptions, TrainRequest
    from kubeml_tpu.control.client import KubemlClient
    from kubeml_tpu.control.deployment import start_deployment

    dep = start_deployment(mesh=mesh8)
    try:
        client = KubemlClient(dep.controller_url)
        rng = np.random.RandomState(0)
        # raw 0..255 uint8 uploads, like a real MNIST ingest
        paths = {}
        for split, n in (("train", 256), ("test", 64)):
            x = rng.randint(0, 256, (n, 28, 28)).astype(np.uint8)
            y = rng.randint(0, 10, n).astype(np.int64)
            np.save(tmp_path / f"x_{split}.npy", x)
            np.save(tmp_path / f"y_{split}.npy", y)
            paths[split] = (str(tmp_path / f"x_{split}.npy"),
                            str(tmp_path / f"y_{split}.npy"))
        client.v1().datasets().create("mnist", paths["train"][0],
                                      paths["train"][1], paths["test"][0],
                                      paths["test"][1])
        client.v1().functions().create(
            "lenet-example", os.path.join(EXAMPLES, "function_lenet.py"))
        req = TrainRequest(model_type="lenet-example", batch_size=32,
                           epochs=1, dataset="mnist", lr=0.05,
                           function_name="lenet-example",
                           options=TrainOptions(default_parallelism=2,
                                                static_parallelism=True,
                                                k=2))
        job_id = client.v1().networks().train(req)
        from tests.test_control_plane import wait_history
        history = wait_history(client, job_id, timeout=240)
        assert len(history.data.train_loss) == 1
        assert np.isfinite(history.data.train_loss).all()
    finally:
        dep.stop()


def test_two_jobs_run_concurrently(tmp_home, tmp_path, mesh8):
    """The reference runs jobs concurrently (one pod each); the threaded
    PS must handle overlapping jobs on one mesh."""
    from tests.test_control_plane import wait_history, write_blob_files
    from kubeml_tpu.api.types import TrainOptions, TrainRequest
    from kubeml_tpu.control.client import KubemlClient
    from kubeml_tpu.control.deployment import start_deployment

    dep = start_deployment(mesh=mesh8)
    try:
        client = KubemlClient(dep.controller_url)
        paths = write_blob_files(tmp_path)
        client.v1().datasets().create("blobs", paths["xtr"], paths["ytr"],
                                      paths["xte"], paths["yte"])
        req = TrainRequest(model_type="mlp", batch_size=32, epochs=2,
                           dataset="blobs", lr=0.1,
                           options=TrainOptions(default_parallelism=2,
                                                static_parallelism=True,
                                                k=2))
        ids = [client.v1().networks().train(req) for _ in range(2)]
        assert len(set(ids)) == 2
        for jid in ids:
            history = wait_history(client, jid, timeout=240)
            assert len(history.data.train_loss) == 2
    finally:
        dep.stop()


def test_gpt_example_trains_end_to_end(tmp_home, tmp_path, mesh8):
    """The LM example: token-window dataset (placeholder labels), causal
    LM training and validation through the full control plane."""
    from kubeml_tpu.api.types import TrainOptions, TrainRequest
    from kubeml_tpu.control.client import KubemlClient
    from kubeml_tpu.control.deployment import start_deployment

    dep = start_deployment(mesh=mesh8)
    try:
        client = KubemlClient(dep.controller_url)
        rng = np.random.RandomState(0)
        paths = {}
        for split, n in (("train", 128), ("test", 32)):
            # ascending byte runs (learnable), ids in [1, 256]
            start = rng.randint(1, 256, size=(n, 1))
            x = ((start + np.arange(32)[None, :] - 1) % 256 + 1
                 ).astype(np.int32)
            y = np.zeros(n, np.int64)  # placeholder (targets = shifted x)
            np.save(tmp_path / f"x_{split}.npy", x)
            np.save(tmp_path / f"y_{split}.npy", y)
            paths[split] = (str(tmp_path / f"x_{split}.npy"),
                            str(tmp_path / f"y_{split}.npy"))
        client.v1().datasets().create("tinytext", paths["train"][0],
                                      paths["train"][1], paths["test"][0],
                                      paths["test"][1])
        client.v1().functions().create(
            "gpt-example", os.path.join(EXAMPLES, "function_gpt.py"))
        req = TrainRequest(model_type="gpt-example", batch_size=16,
                           epochs=1, dataset="tinytext", lr=0.003,
                           function_name="gpt-example",
                           options=TrainOptions(default_parallelism=2,
                                                static_parallelism=True,
                                                k=2, validate_every=1))
        job_id = client.v1().networks().train(req)
        from tests.test_control_plane import wait_history
        history = wait_history(client, job_id, timeout=240)
        assert len(history.data.train_loss) == 1
        assert np.isfinite(history.data.train_loss).all()
        # validation ran: next-token accuracy is a real number in [0, 100]
        assert 0.0 <= history.data.accuracy[0] <= 100.0
    finally:
        dep.stop()
