"""Worker program for the 2-process FULL-TrainJob test (VERDICT r2 item 3).

Round 2's 2-process proof covered one K-avg round + checkpoint
(dist_worker_main.py); the reference runs its entire per-job loop across
process boundaries in production (ml/pkg/ps/job_pod.go:66-217). This
worker drives the REAL TrainJob epoch loop — epochs, dynamic parallelism
(scripted scheduler callback), validation cadence, history persistence,
checkpointing — as one SPMD program over a jax.distributed CPU cluster:
every process executes the identical host loop in lockstep while the
engine's merge psum crosses the process boundary each round.

Launched by tools/launch_distributed (2 processes x 4 virtual CPU
devices). Each process uses an isolated KUBEML_TPU_HOME under
<outdir>/p<pid> (no filesystem races) and saves its history record for
the parent test to compare across processes and against the
single-process reference run.
"""
import faulthandler
import json
import os
import sys

# a cross-process deadlock here would otherwise be invisible: dump every
# thread's Python stack periodically so the parent test's captured output
# shows WHERE the processes are stuck
faulthandler.dump_traceback_later(120, repeat=True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from kubeml_tpu.parallel.distributed import initialize  # noqa: E402

# env-driven join (KUBEML_COORDINATOR_ADDRESS et al. from the launcher).
# MUST precede any other JAX call.
initialize()

import jax  # noqa: E402
import numpy as np  # noqa: E402


def main(outdir: str) -> None:
    pid = jax.process_index()
    os.environ["KUBEML_TPU_HOME"] = os.path.join(outdir, f"p{pid}")

    from kubeml_tpu.data.registry import DatasetRegistry
    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.parallel.distributed import make_multislice_mesh
    from kubeml_tpu.train.history import HistoryStore
    from kubeml_tpu.train.job import JobCallbacks, TrainJob
    from tests.test_job import ToyDataset, make_blobs, make_task

    assert jax.process_count() == 2
    mesh = make_multislice_mesh()
    print(f"[rank {pid}] cluster up, mesh built", flush=True)

    reg = DatasetRegistry()
    make_blobs(reg)  # deterministic seed: identical data on every process
    store = HistoryStore()
    model = get_builtin("mlp")(hidden=16, num_classes=4)

    # dynamic parallelism: a scripted scheduler (deterministic, identical
    # on both processes) grows N 2 -> 4 -> 8 across epochs, forcing the
    # engine to re-lower its round program mid-job over the live cluster
    import time
    t0 = time.time()
    schedule = iter([4, 8, 8])

    def _req(task):
        print(f"[rank {pid}] epoch done t={time.time() - t0:.1f}s",
              flush=True)
        return next(schedule, None)

    callbacks = JobCallbacks(
        request_parallelism=_req,
        publish_metrics=lambda m: print(
            f"[rank {pid}] metrics N={m.parallelism} "
            f"loss={m.train_loss:.4f} t={time.time() - t0:.1f}s",
            flush=True))

    task = make_task(job_id="distjob2", epochs=3, parallelism=2, k=2,
                     batch=32, lr=0.1, static=False, validate_every=1)
    job = TrainJob(task, model, ToyDataset(), mesh, registry=reg,
                   history_store=store, callbacks=callbacks)
    record = job.train()

    assert record.data.parallelism == [2, 4, 8], record.data.parallelism
    assert len(record.data.train_loss) == 3

    with open(os.path.join(outdir, f"history_p{pid}.json"), "w") as f:
        json.dump({
            "train_loss": [float(v) for v in record.data.train_loss],
            "accuracy": [float(v) for v in record.data.accuracy],
            "validation_loss": [float(v) for v in
                                record.data.validation_loss],
            "parallelism": list(record.data.parallelism),
        }, f)

    # the final checkpoint must be loadable in-process (every process
    # wrote its own home; replicated weights => identical content)
    from kubeml_tpu.train.checkpoint import load_checkpoint
    variables, manifest = load_checkpoint("distjob2")
    assert manifest["model"] == "mlp"
    leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(variables)]
    np.savez(os.path.join(outdir, f"final_p{pid}.npz"),
             **{str(i): l for i, l in enumerate(leaves)})
    print(f"jobproc {pid} OK", flush=True)


if __name__ == "__main__":
    main(sys.argv[1])
