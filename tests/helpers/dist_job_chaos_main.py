"""Worker program for the 2-process TrainJob CHAOS tests.

ONE launch — the supervisor owns recovery (VERDICT r4 item 2). The
incarnation is selected by the launcher's restart counter
($KUBEML_RESTART_COUNT, tools/launch_distributed.py supervisor mode),
and $CHAOS_CRASHES (default 1) sets how many incarnations crash before
one runs to completion:

  incarnation i < CHAOS_CRASHES — run the full-TrainJob loop; at THIS
       incarnation's second between-epoch scheduler callback, each rank
       first waits for one epoch of NEW checkpoint progress to be
       durable (manifest epoch >= this incarnation's start epoch + 1 —
       the same make-progress-between-crashes discipline as the
       single-process chained test), then rank 1 SIGKILLs itself. Rank
       0 blocks in the next cross-process collective; --fail-fast
       tears the cluster down and the SUPERVISOR relaunches it.
  incarnation i >= CHAOS_CRASHES — resume from the job's own
       checkpoint and run to completion; the final history must be
       continuous across EVERY crash. No human (or test harness)
       issues any resume — that is the point.

$CHAOS_EPOCHS (default 3) sizes the job; a crashing incarnation
starting at epoch s needs epochs >= s + 3 so it has two callbacks.
The scripted parallelism trajectory is 2 -> 4 -> 8 -> 8 ... (value
for epoch s+1 delivered at the callback after epoch s); a resumed
incarnation continues the trajectory from its manifest epoch.

The reference survives function-pod death only within a single merge
(ml/pkg/train/util.go:144-166) and relies on k8s re-creating the
TrainJob pod (ml/pkg/ps/job_pod.go:18-62); supervisor restart + the
checkpoint manifest is that loop, process-shaped.
"""
import faulthandler
import json
import os
import signal
import sys
import time

faulthandler.dump_traceback_later(120, repeat=True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from kubeml_tpu.parallel.distributed import initialize  # noqa: E402

initialize()

import jax  # noqa: E402

JOB_ID = "distjobc"


def main(outdir: str) -> None:
    pid = jax.process_index()
    incarnation = int(os.environ.get("KUBEML_RESTART_COUNT", "0"))
    crashes = int(os.environ.get("CHAOS_CRASHES", "1"))
    epochs = int(os.environ.get("CHAOS_EPOCHS", "3"))
    os.environ["KUBEML_TPU_HOME"] = os.path.join(outdir, f"p{pid}")

    from kubeml_tpu.data.registry import DatasetRegistry
    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.parallel.distributed import make_multislice_mesh
    from kubeml_tpu.train.history import HistoryStore
    from kubeml_tpu.train.job import JobCallbacks, TrainJob
    from tests.test_job import ToyDataset, make_blobs, make_task

    assert jax.process_count() == 2
    mesh = make_multislice_mesh()
    print(f"[rank {pid}] cluster up, incarnation={incarnation}", flush=True)

    reg = DatasetRegistry()
    if incarnation == 0:  # restarts reuse the home (and its dataset files)
        make_blobs(reg)  # deterministic seed: identical data everywhere
    store = HistoryStore()
    model = get_builtin("mlp")(hidden=16, num_classes=4)

    manifest_path = os.path.join(os.environ["KUBEML_TPU_HOME"], "models",
                                 JOB_ID, "manifest.json")

    def manifest_epoch() -> int:
        try:
            with open(manifest_path) as f:
                return int(json.load(f).get("epoch") or 0)
        except (OSError, ValueError):
            return 0

    # scripted trajectory: epoch s trains at traj_full[s]
    traj_full = [2, 4] + [8] * (epochs - 2)
    start = 0 if incarnation == 0 else manifest_epoch()
    # callback after epoch s delivers traj_full[s + 1]
    schedule = iter(traj_full[start + 1:])

    task = make_task(job_id=JOB_ID, epochs=epochs, parallelism=2, k=2,
                     batch=32, lr=0.1, static=False, validate_every=1)
    if incarnation > 0:
        assert start >= 1, "no durable checkpoint to resume from"
        task.parameters.resume_from = JOB_ID

    if incarnation < crashes:
        # crash at THIS incarnation's second callback, after one epoch
        # of NEW durable checkpoint progress (manifest >= start + 1):
        # every crash-restart cycle advances the recoverable state
        calls = {"n": 0}

        def _req(task):
            calls["n"] += 1
            if calls["n"] == 2:
                deadline = time.time() + 120
                while manifest_epoch() < start + 1:
                    assert time.time() < deadline, \
                        "post-crash checkpoint never became durable"
                    time.sleep(0.05)
                if pid == 1:
                    print(f"[rank {pid}] chaos: SIGKILL self "
                          f"(incarnation {incarnation})", flush=True)
                    sys.stdout.flush()
                    os.kill(os.getpid(), signal.SIGKILL)
            return next(schedule, None)

        def _metrics(m):
            # record pre-crash epoch metrics for the parent test's
            # continuity check (epochs completed BEFORE the crash point)
            with open(os.path.join(outdir, f"crash_metrics_p{pid}.jsonl"),
                      "a") as f:
                f.write(json.dumps({"train_loss": float(m.train_loss),
                                    "parallelism": m.parallelism}) + "\n")

        job = TrainJob(task, model, ToyDataset(), mesh, registry=reg,
                       history_store=store,
                       callbacks=JobCallbacks(request_parallelism=_req,
                                              publish_metrics=_metrics))
        job.train()
        raise AssertionError(
            f"incarnation {incarnation} completed without crashing")

    # ---- final incarnation: resume and run to completion
    job = TrainJob(task, model, ToyDataset(), mesh, registry=reg,
                   history_store=store,
                   callbacks=JobCallbacks(
                       request_parallelism=lambda t: next(schedule, None)))
    record = job.train()

    # continuous across every crash: all epochs present, the scripted
    # trajectory intact (earlier epochs restored from the manifest, the
    # negotiated parallelism carried over)
    assert len(record.data.train_loss) == epochs, record.data.train_loss
    assert record.data.parallelism == traj_full[:epochs], \
        record.data.parallelism

    with open(os.path.join(outdir, f"resume_history_p{pid}.json"),
              "w") as f:
        json.dump({
            "train_loss": [float(v) for v in record.data.train_loss],
            "accuracy": [float(v) for v in record.data.accuracy],
            "parallelism": list(record.data.parallelism),
        }, f)
    print(f"chaosproc {pid} OK", flush=True)


if __name__ == "__main__":
    main(sys.argv[1])
