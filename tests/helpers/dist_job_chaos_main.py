"""Worker program for the 2-process TrainJob CHAOS test.

ONE phase — the supervisor owns recovery (VERDICT r4 item 2). The
incarnation is selected by the launcher's restart counter
($KUBEML_RESTART_COUNT, tools/launch_distributed.py supervisor mode):

  0 (first launch) — run the full-TrainJob loop (same as
       dist_job_main.py); at the between-epoch scheduler callback AFTER
       epoch 2's training (the second callback), each rank first waits
       for its own epoch-1 checkpoint to be durable, then rank 1
       SIGKILLs itself — the worker-process-death scenario. Rank 0
       proceeds into the next epoch and blocks in the first
       cross-process collective; the launcher's --fail-fast kills it,
       and the SUPERVISOR relaunches the cluster.
  >0 (supervisor restart) — resume the SAME job id from its own
       checkpoint: the TrainJob restores the completed epochs' history,
       epoch index, and negotiated parallelism from the manifest and
       runs the job to completion. The final history must be continuous
       across the crash. No human (or test harness) issues the resume —
       that is the point.

The reference survives function-pod death only within a single merge
(ml/pkg/train/util.go:144-166) and relies on k8s re-creating the
TrainJob pod (ml/pkg/ps/job_pod.go:18-62); supervisor restart + the
checkpoint manifest is that loop, process-shaped.
"""
import faulthandler
import json
import os
import signal
import sys
import time

faulthandler.dump_traceback_later(120, repeat=True)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from kubeml_tpu.parallel.distributed import initialize  # noqa: E402

initialize()

import jax  # noqa: E402

JOB_ID = "distjobc"


def main(outdir: str) -> None:
    pid = jax.process_index()
    incarnation = int(os.environ.get("KUBEML_RESTART_COUNT", "0"))
    os.environ["KUBEML_TPU_HOME"] = os.path.join(outdir, f"p{pid}")

    from kubeml_tpu.data.registry import DatasetRegistry
    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.parallel.distributed import make_multislice_mesh
    from kubeml_tpu.train.history import HistoryStore
    from kubeml_tpu.train.job import JobCallbacks, TrainJob
    from tests.test_job import ToyDataset, make_blobs, make_task

    assert jax.process_count() == 2
    mesh = make_multislice_mesh()
    print(f"[rank {pid}] cluster up, incarnation={incarnation}", flush=True)

    reg = DatasetRegistry()
    if incarnation == 0:  # restarts reuse the home (and its dataset files)
        make_blobs(reg)  # deterministic seed: identical data everywhere
    store = HistoryStore()
    model = get_builtin("mlp")(hidden=16, num_classes=4)

    manifest_path = os.path.join(os.environ["KUBEML_TPU_HOME"], "models",
                                 JOB_ID, "manifest.json")

    def manifest_epoch() -> int:
        try:
            with open(manifest_path) as f:
                return int(json.load(f).get("epoch") or 0)
        except (OSError, ValueError):
            return 0

    task = make_task(job_id=JOB_ID, epochs=3, parallelism=2, k=2,
                     batch=32, lr=0.1, static=False, validate_every=1)

    if incarnation == 0:
        # full schedule 2 -> 4 -> 8; the crash lands at the SECOND
        # between-epoch callback (after epoch 2's training, before its
        # checkpoint), so the durable state at death is the epoch-1
        # checkpoint carrying history[:1] and next-parallelism 4
        schedule = iter([4, 8])
        calls = {"n": 0}

        def _req(task):
            calls["n"] += 1
            if calls["n"] == 2:
                deadline = time.time() + 120
                while manifest_epoch() < 1:
                    assert time.time() < deadline, \
                        "epoch-1 checkpoint never became durable"
                    time.sleep(0.05)
                if pid == 1:
                    print(f"[rank {pid}] chaos: SIGKILL self", flush=True)
                    sys.stdout.flush()
                    os.kill(os.getpid(), signal.SIGKILL)
            return next(schedule, None)

        def _metrics(m):
            # record the pre-crash epoch metrics for the parent test's
            # continuity check (only epoch 1's reaches this point)
            with open(os.path.join(outdir, f"crash_metrics_p{pid}.jsonl"),
                      "a") as f:
                f.write(json.dumps({"train_loss": float(m.train_loss),
                                    "parallelism": m.parallelism}) + "\n")

        job = TrainJob(task, model, ToyDataset(), mesh, registry=reg,
                       history_store=store,
                       callbacks=JobCallbacks(request_parallelism=_req,
                                              publish_metrics=_metrics))
        job.train()
        raise AssertionError("first incarnation completed without crashing")

    # ---- supervisor-restart incarnation: resume from own checkpoint
    assert manifest_epoch() >= 1, "no durable checkpoint to resume from"
    schedule = iter([8])
    task.parameters.resume_from = JOB_ID
    job = TrainJob(task, model, ToyDataset(), mesh, registry=reg,
                   history_store=store,
                   callbacks=JobCallbacks(
                       request_parallelism=lambda t: next(schedule, None)))
    record = job.train()

    # continuous across the crash: all 3 epochs present, the scripted
    # 2 -> 4 -> 8 trajectory intact (epoch 1 restored, N=4 carried over
    # from the manifest)
    assert len(record.data.train_loss) == 3, record.data.train_loss
    assert record.data.parallelism == [2, 4, 8], record.data.parallelism

    with open(os.path.join(outdir, f"resume_history_p{pid}.json"),
              "w") as f:
        json.dump({
            "train_loss": [float(v) for v in record.data.train_loss],
            "accuracy": [float(v) for v in record.data.accuracy],
            "parallelism": list(record.data.parallelism),
        }, f)
    print(f"chaosproc {pid} OK", flush=True)


if __name__ == "__main__":
    main(sys.argv[1])
