"""Worker program for the 2-process jax.distributed test.

Launched (2 processes x 4 virtual CPU devices) by
tools/launch_distributed.py, which provides the KUBEML_* cluster env.
Each process: joins the cluster, builds the slice-major multislice mesh,
runs ONE K-avg sync round whose merge psum crosses the process boundary,
and participates in a cluster-wide checkpoint (coordinator writes, all
load back). Saves its view of the averaged weights for the parent test
to compare across processes and against a single-process reference.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax  # noqa: E402

from kubeml_tpu.parallel.distributed import (initialize,  # noqa: E402
                                             is_coordinator,
                                             make_multislice_mesh)

# env-driven join (KUBEML_COORDINATOR_ADDRESS et al. from the launcher).
# MUST precede any other JAX call.
initialize()

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from kubeml_tpu.models import get_builtin  # noqa: E402
from kubeml_tpu.parallel.kavg import KAvgEngine  # noqa: E402
from kubeml_tpu.train.checkpoint import (load_checkpoint,  # noqa: E402
                                         save_checkpoint)


def main(outdir: str) -> None:
    nproc = int(os.environ["KUBEML_NUM_PROCESSES"])
    assert jax.process_count() == nproc, jax.process_count()
    per = int(os.environ["JAX_NUM_CPU_DEVICES"])
    assert len(jax.local_devices()) == per
    assert len(jax.devices()) == nproc * per

    mesh = make_multislice_mesh()
    model = get_builtin("mlp")(hidden=16, num_classes=4)
    rng = np.random.RandomState(0)  # identical data on every process
    W, S, B, D = 8, 2, 4, 8
    x = rng.randn(W, S, B, D).astype(np.float32)
    y = rng.randint(0, 4, size=(W, S, B)).astype(np.int32)
    rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x[0, 0])})
    # host-side (uncommitted) values: every process passes the same full
    # array and jit forms each global array from the local slices — no
    # cross-host transfer
    variables = jax.tree_util.tree_map(np.asarray, variables)

    engine = KAvgEngine(mesh, model.loss, model.metrics,
                        model.configure_optimizers, donate=False)
    avg, stats = engine.train_round(
        variables, {"x": x, "y": y},
        sample_mask=np.ones((W, S, B), np.float32),
        step_mask=np.ones((W, S), np.float32),
        worker_mask=np.ones(W, np.float32),
        rngs=rngs, lr=0.1, epoch=0)
    assert stats.contributors == W
    # the averaged model is replicated (out_specs P()) => every process
    # can read its local copy
    leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(avg)]

    pid = jax.process_index()
    np.savez(os.path.join(outdir, f"avg_p{pid}.npz"),
             **{str(i): l for i, l in enumerate(leaves)})

    # cluster-wide checkpoint: coordinator writes, everyone syncs + loads
    from jax.experimental import multihost_utils
    root = os.path.join(outdir, "models")
    if is_coordinator():
        save_checkpoint("distjob1", avg,
                        {"model": "mlp", "function": "mlp",
                         "dataset": "synth"}, root=root)
    multihost_utils.sync_global_devices("kubeml_ckpt_done")
    restored, manifest = load_checkpoint("distjob1", root=root)
    assert manifest["model"] == "mlp"
    for a, b in zip(leaves, [np.asarray(l) for l in
                             jax.tree_util.tree_leaves(restored)]):
        np.testing.assert_array_equal(a, b)
    print(f"proc {pid} OK", flush=True)


if __name__ == "__main__":
    main(sys.argv[1])
