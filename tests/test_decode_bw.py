"""Decode-bandwidth tests (PR 15): pallas paged attention + int8 KV.

The two new serving-path variants registered in
engine.SERVE_PATH_VARIANTS are pinned here, quoted, next to exactness
assertions (tools/check_serve_parity.py enforces this coupling):

  * 'pallas_paged' — the paged-attention kernel (interpret mode on CPU)
    is BIT-IDENTICAL to the gather-based reference programs, at the op
    level and through a full engine lifecycle (joins, leaves, mixed
    prompt lengths, copy-on-write splits), with the same dispatch and
    compile counts — the kernel is a bandwidth lever, not a math change.
  * 'int8_kv' — quantized KV pages keep the row-independence contract:
    a stream's tokens are identical solo vs continuously batched, the
    prefix cache serves quantized pages, CoW splits carry per-page
    scales, and the pager invariants hold through hot-swap retirement.

Plus the deterministic bytes-per-token comm proxy (page geometry x
storage dtype, never a timer): slab/engine/stat/metric/snapshot all
agree on the same number, and int8 cuts it >= 3.5x for an f32 model.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.serving


def _nano():
    import jax

    from kubeml_tpu.models import get_builtin
    model = get_builtin("gpt-nano")()
    module = model.module
    variables = model.init_variables(
        jax.random.PRNGKey(0),
        {"x": np.ones((1, module.max_len), np.int32)})
    return model, module, variables


def _drive(engine, limit=10_000):
    finished = []
    while engine.active():
        finished.extend(engine.step())
        limit -= 1
        assert limit > 0, "engine failed to drain"
    return finished


def _rand_paged(key, S, Pmax, G, H, D, dtype, T, quantized):
    """Random paged-attention operands with realistic masking: page 0
    reserved (tails), per-slot valid prefix, NEG_INF bias."""
    import jax
    import jax.numpy as jnp

    from kubeml_tpu.ops.attention import NEG_INF
    P = S * Pmax + 1
    C = Pmax * G
    ks = jax.random.split(key, 6)
    q = jax.random.normal(ks[0], (S, T, H, D), jnp.float32).astype(dtype)
    if quantized:
        k_pages = jax.random.randint(ks[1], (P, G, H, D), -127, 128,
                                     jnp.int32).astype(jnp.int8)
        v_pages = jax.random.randint(ks[2], (P, G, H, D), -127, 128,
                                     jnp.int32).astype(jnp.int8)
        k_scale = jax.random.uniform(ks[3], (P,), jnp.float32, 0.001, 0.1)
        v_scale = jax.random.uniform(ks[4], (P,), jnp.float32, 0.001, 0.1)
    else:
        k_pages = jax.random.normal(ks[1], (P, G, H, D),
                                    jnp.float32).astype(dtype)
        v_pages = jax.random.normal(ks[2], (P, G, H, D),
                                    jnp.float32).astype(dtype)
        k_scale = jnp.zeros((P,), jnp.float32)
        v_scale = jnp.zeros((P,), jnp.float32)
    # slot s holds s+1 pages, the rest of its table points at null 0
    tables = np.zeros((S, Pmax), np.int32)
    for s in range(S):
        for j in range(min(s + 1, Pmax)):
            tables[s, j] = 1 + s * Pmax + j
    n_valid = np.minimum(np.arange(1, S + 1) * G, C)
    keep = (np.arange(C)[None, :] < n_valid[:, None]).astype(np.float32)
    bias = ((1.0 - keep) * NEG_INF)[:, None, None, :]
    bias = np.broadcast_to(bias, (S, 1, T, C))
    return (q, k_pages, v_pages, k_scale, v_scale,
            jnp.asarray(tables), jnp.asarray(bias))


# ------------------------------------------------------- kernel parity

def test_pallas_paged_kernel_bit_identical_to_gather():
    """'pallas_paged': the kernel (interpret) reproduces the gather
    reference BIT-FOR-BIT — f32 and bf16, single-token decode and
    chunked-prefill query shapes."""
    import functools

    import jax
    import jax.numpy as jnp

    from kubeml_tpu.ops.pallas.paged_attention import paged_attention

    for seed, dtype, T in ((0, jnp.float32, 1), (1, jnp.float32, 16),
                           (2, jnp.bfloat16, 1), (3, jnp.bfloat16, 16)):
        args = _rand_paged(jax.random.PRNGKey(seed), S=4, Pmax=4, G=8,
                           H=4, D=64, dtype=dtype, T=T, quantized=False)
        ker = jax.jit(functools.partial(paged_attention, impl="pallas",
                                        interpret=True))(*args)
        ref = jax.jit(functools.partial(
            paged_attention, impl="gather"))(*args)
        np.testing.assert_array_equal(np.asarray(ker), np.asarray(ref))


def test_pallas_paged_kernel_int8_dequant_bit_identical():
    """int8 pages: the kernel's in-VMEM dequant and the gather path's
    pre-gather dequant are ONE expression — outputs bit-identical."""
    import functools

    import jax
    import jax.numpy as jnp

    from kubeml_tpu.ops.pallas.paged_attention import paged_attention

    for seed, dtype, T in ((4, jnp.float32, 1), (5, jnp.bfloat16, 16)):
        args = _rand_paged(jax.random.PRNGKey(seed), S=3, Pmax=3, G=8,
                           H=2, D=32, dtype=dtype, T=T, quantized=True)
        ker = jax.jit(functools.partial(
            paged_attention, quantized=True, compute_dtype=dtype,
            impl="pallas", interpret=True))(*args)
        ref = jax.jit(functools.partial(
            paged_attention, quantized=True, compute_dtype=dtype,
            impl="gather"))(*args)
        np.testing.assert_array_equal(np.asarray(ker), np.asarray(ref))


def test_paged_attention_validates_impl_and_geometry():
    import jax
    import jax.numpy as jnp

    from kubeml_tpu.ops.pallas.paged_attention import (paged_attention,
                                                       paged_eligible)
    assert paged_eligible(8) and paged_eligible(16)
    assert not paged_eligible(4)
    args = _rand_paged(jax.random.PRNGKey(0), S=2, Pmax=2, G=4, H=2,
                       D=8, dtype=jnp.float32, T=1, quantized=False)
    with pytest.raises(ValueError, match="impl"):
        paged_attention(*args, impl="mosaic")
    with pytest.raises(ValueError, match="sublane"):
        paged_attention(*args, impl="pallas", interpret=True)


# --------------------------------------------------- engine-level parity

def _staggered_run(module, variables, **engine_kw):
    """A lifecycle covering joins, leaves, mixed prompt lengths, a
    prefix-cache hit, and a CoW split; returns (engine, requests)."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    engine = DecodeEngine(module, variables, slots=4, page=8,
                          prefill_chunk=8, **engine_kw)
    shared = list(range(5, 21))                    # 16 tokens = 2 pages
    a = GenerateRequest(list(shared), max_new_tokens=6, temperature=0.0,
                        seed=0)
    b = GenerateRequest(list(range(40, 43)), max_new_tokens=10,
                        temperature=0.9, seed=3)
    engine.attach(a)
    engine.attach(b)
    for _ in range(4):                              # join mid-flight
        engine.step()
    # same prompt -> prefix-cache hit over shared pages; its first
    # generated token writes into a shared page -> CoW split
    c = GenerateRequest(list(shared), max_new_tokens=6, temperature=0.0,
                        seed=0)
    engine.attach(c)
    _drive(engine)
    return engine, [a, b, c]


def test_pallas_paged_engine_bit_identical_across_lifecycle():
    """'pallas_paged' at engine scope: forcing the kernel (interpret)
    changes NOTHING observable vs the gather programs — identical
    tokens through joins/leaves/prompt lengths/cache hits/CoW, and
    identical dispatch/compile counts (still exactly two programs)."""
    _model, module, variables = _nano()
    g_eng, g_reqs = _staggered_run(module, variables)
    p_eng, p_reqs = _staggered_run(module, variables,
                                   attn_impl="pallas",
                                   attn_interpret=True)
    assert all(r.outcome == "ok" for r in g_reqs + p_reqs)
    for a, b in zip(g_reqs, p_reqs):
        np.testing.assert_array_equal(np.asarray(a.tokens),
                                      np.asarray(b.tokens))
    # the lifecycle really exercised the cache + CoW paths
    assert g_eng.stats["prefix_hits"] > 0
    assert g_eng.stats["cow_splits"] >= 1
    for stat in ("dispatches", "compiles", "prefill_dispatches",
                 "prefill_compiles", "cow_splits", "prefix_hits"):
        assert p_eng.stats[stat] == g_eng.stats[stat], stat
    assert p_eng.stats["compiles"] == 1
    assert p_eng.stats["prefill_compiles"] == 1
    g_eng.check_pager()
    p_eng.check_pager()


# ----------------------------------------------------------- int8 pages

def test_int8_kv_bit_identical_solo_vs_concurrent():
    """'int8_kv': quantized pages keep the row-independence contract —
    a stream's tokens are identical whether it shares the engine with
    neighbours or runs alone (pages disjoint, per-page scales private,
    sampling keys per (seed, pos))."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    specs = [([5, 6, 7, 8, 9], 6, 0.0, 0),
             ([9, 10, 11, 12], 8, 0.7, 1),
             ([3, 4], 4, 1.3, 7)]

    def make():
        return [GenerateRequest(list(p), max_new_tokens=n, temperature=t,
                                seed=s) for p, n, t, s in specs]

    packed = DecodeEngine(module, variables, slots=4, page=8,
                          prefill_chunk=8, kv_dtype="int8")
    reqs_packed = make()
    for r in reqs_packed:
        packed.attach(r)
    _drive(packed)

    alone = DecodeEngine(module, variables, slots=4, page=8,
                         prefill_chunk=8, kv_dtype="int8")
    reqs_alone = make()
    for r in reqs_alone:
        alone.attach(r)
        _drive(alone)

    assert all(r.outcome == "ok" for r in reqs_packed + reqs_alone)
    for a, b in zip(reqs_packed, reqs_alone):
        np.testing.assert_array_equal(np.asarray(a.tokens),
                                      np.asarray(b.tokens))


def test_int8_kv_page_lifecycle_and_invariants():
    """int8 pages through the whole pager lifecycle: the slab stores
    int8 with [L, P] f32 scale sidecars, the prefix cache serves
    QUANTIZED pages (hit tokens == miss tokens exactly), CoW splits
    carry scales with their page, and the pager invariants hold
    strictly through release and hot-swap generation retirement."""
    import jax.numpy as jnp

    _model, module, variables = _nano()
    eng, reqs = _staggered_run(module, variables, kv_dtype="int8")
    assert eng.slab.k.dtype == jnp.int8
    assert eng.slab.v.dtype == jnp.int8
    assert eng.slab.k_scale.shape == (module.layers, eng.geom.pages)
    assert eng.slab.k_scale.dtype == jnp.float32
    assert eng.stats["prefix_hits"] > 0          # hit served int8 pages
    assert eng.stats["cow_splits"] >= 1          # split carried scales
    assert all(r.outcome == "ok" for r in reqs)
    # cache-hit stream (same greedy prompt) decoded the SAME tokens
    # from shared quantized pages as the cold stream wrote
    np.testing.assert_array_equal(np.asarray(reqs[0].tokens),
                                  np.asarray(reqs[2].tokens))
    eng.check_pager()                            # strict: raises on leak
    assert eng.stats["page_leaks"] == 0
    # hot-swap: old generation's pages (and their scale state) retire
    # cleanly once the last pre-swap stream drains
    from kubeml_tpu.serve.slots import GenerateRequest
    pre = GenerateRequest(list(range(5, 13)), max_new_tokens=4)
    eng.attach(pre)
    eng.step()
    eng.install_weights(variables)
    post = GenerateRequest(list(range(20, 26)), max_new_tokens=4)
    eng.attach(post)
    _drive(eng)
    assert eng.stats["generations_retired"] >= 1
    eng.check_pager()
    # nothing is referenced once every stream drained: what remains
    # resident is only reclaimable prefix-cache pages
    assert eng.pager.in_use == 0
    assert eng.pager.cached_pages == eng.pager.evictable_pages


def test_int8_quantize_roundtrip_per_page_scales():
    """The quantize-on-write helper's contract, directly: round-trip
    within half a quantization step, scale growth requantizes earlier
    rows under the new scale, and an offset-0 write WIPES a reused
    page's stale scale instead of maxing against it."""
    import jax.numpy as jnp

    from kubeml_tpu.models.gpt import _int8_write_decode

    L, P, G, H, D = 1, 3, 4, 2, 4
    pages = jnp.zeros((L, P, G, H, D), jnp.int8)
    scales = jnp.zeros((L, P), jnp.float32)
    row0 = jnp.full((1, H, D), 0.5, jnp.float32)
    pages, scales = _int8_write_decode(
        pages, scales, 0, row0, jnp.array([1]), jnp.array([0]))
    s0 = float(scales[0, 1])
    assert s0 == pytest.approx(0.5 / 127.0)
    got = np.asarray(pages[0, 1, 0], np.float32) * s0
    np.testing.assert_allclose(got, np.asarray(row0[0]), atol=s0 / 2)
    # a larger row grows the scale; row 0 is requantized, still within
    # half of the NEW step
    row1 = jnp.full((1, H, D), 2.0, jnp.float32)
    pages, scales = _int8_write_decode(
        pages, scales, 0, row1, jnp.array([1]), jnp.array([1]))
    s1 = float(scales[0, 1])
    assert s1 == pytest.approx(2.0 / 127.0)
    got0 = np.asarray(pages[0, 1, 0], np.float32) * s1
    np.testing.assert_allclose(got0, np.asarray(row0[0]), atol=s1 / 2)
    got1 = np.asarray(pages[0, 1, 1], np.float32) * s1
    np.testing.assert_allclose(got1, np.asarray(row1[0]), atol=s1 / 2)
    # page reuse: the first write of a page always lands at offset 0,
    # which resets the stale scale (no max against dead data)
    tiny = jnp.full((1, H, D), 0.01, jnp.float32)
    pages, scales = _int8_write_decode(
        pages, scales, 0, tiny, jnp.array([1]), jnp.array([0]))
    assert float(scales[0, 1]) == pytest.approx(0.01 / 127.0)


def test_kv_dtype_validated_everywhere():
    import jax.numpy as jnp

    from kubeml_tpu.models.gpt import (build_paged_decode_step,
                                       build_paged_prefill_step)
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.pager import KVPageSlab, PageGeometry

    _model, module, variables = _nano()
    with pytest.raises(ValueError, match="kv_dtype"):
        build_paged_decode_step(module, kv_dtype="fp8")
    with pytest.raises(ValueError, match="kv_dtype"):
        build_paged_prefill_step(module, 8, kv_dtype="fp8")
    with pytest.raises(ValueError, match="kv_dtype"):
        KVPageSlab(PageGeometry(slots=2, page=8, pages=5,
                                pages_per_slot=2),
                   1, 2, 4, jnp.float32, kv_dtype="fp8")
    with pytest.raises(ValueError, match="kv_dtype"):
        DecodeEngine(module, variables, kv_dtype="fp8")


# ------------------------------------------------- bytes-per-token proxy

def test_kv_bytes_per_token_proxy_pinned():
    """The comm proxy is pure geometry x dtype: pinned against the
    closed form for both storage modes, and int8 cuts an f32 model's
    per-token KV traffic >= 3.5x."""
    import jax.numpy as jnp

    from kubeml_tpu.serve.pager import KVPageSlab, PageGeometry

    geom = PageGeometry(slots=4, page=16, pages=33, pages_per_slot=8)
    L, H, D = 3, 4, 64
    C = geom.context
    f32 = KVPageSlab(geom, L, H, D, jnp.float32)
    i8 = KVPageSlab(geom, L, H, D, jnp.float32, kv_dtype="int8")
    assert f32.decode_bytes_per_token == L * 2 * (C + 1) * H * D * 4
    assert i8.decode_bytes_per_token == L * (
        2 * (C + 1) * H * D * 1 + 2 * 4 * (geom.pages_per_slot + 1))
    ratio = f32.decode_bytes_per_token / i8.decode_bytes_per_token
    assert ratio >= 3.5
    # sidecars are accounted in device residency too
    assert i8.device_bytes >= f32.k_scale.nbytes + f32.v_scale.nbytes


def test_engine_kv_bytes_stat_is_deterministic():
    """stats['kv_bytes'] advances by exactly decode-lanes x proxy —
    replayable from dispatch accounting, no timers involved."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    eng = DecodeEngine(module, variables, slots=2, page=8,
                       prefill_chunk=8)
    req = GenerateRequest(list(range(5, 14)), max_new_tokens=5)
    eng.attach(req)
    _drive(eng)
    assert eng.stats["kv_bytes"] == \
        eng.stats["decode_tokens"] * eng.kv_bytes_per_token
    assert eng.kv_bytes_per_token == eng.slab.decode_bytes_per_token


# ------------------------------------------------- metrics / snapshot / CLI

def test_kv_bytes_metric_family_and_snapshot():
    """kubeml_serve_kv_bytes_total passes the metrics lint, the service
    delta-advances it from the cumulative engine stat, and the snapshot
    carries the proxy + storage mode for health/top."""
    from kubeml_tpu.metrics.prom import MetricsRegistry
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.service import ServeService
    from tools.check_metrics import validate_exposition

    m = MetricsRegistry()
    m.note_serve_kv_bytes("m1", 4096)
    text = m.exposition()
    assert validate_exposition(text) == []
    assert 'kubeml_serve_kv_bytes_total{model="m1"} 4096' in text
    m.clear_serve("m1")
    assert 'model="m1"' not in m.exposition()

    _model, module, variables = _nano()
    engine = DecodeEngine(module, variables, slots=1, page=8,
                          kv_dtype="int8")
    m2 = MetricsRegistry()
    svc = ServeService("m2", engine, max_queue=1, metrics=m2)  # no loop
    snap = svc.snapshot()
    assert snap["serve_kv_dtype"] == "int8"
    assert snap["serve_kv_bytes_per_token"] == engine.kv_bytes_per_token
    engine.stats["kv_bytes"] = 1000
    svc._publish()
    svc._publish()   # same cumulative value: no double count
    assert 'kubeml_serve_kv_bytes_total{model="m2"} 1000' \
        in m2.exposition()
    engine.stats["kv_bytes"] = 1500
    svc._publish()
    assert 'kubeml_serve_kv_bytes_total{model="m2"} 1500' \
        in m2.exposition()


def test_top_renders_decode_bw_line():
    from kubeml_tpu.cli.main import _render_top

    doc = {"id": "serve:m1", "state": "healthy", "reasons": [],
           "latest": {"serve_active_slots": 1, "serve_slot_cap": 4,
                      "serve_queue_depth": 0, "serve_queue_cap": 8,
                      "serve_kv_page_utilization": 0.5,
                      "serve_kv_bytes_per_token": 16640,
                      "serve_kv_dtype": "int8"}}
    out = _render_top(doc)
    assert "decode bw: 16640 B/token" in out
    assert "kv dtype int8" in out


def test_serve_kv_dtype_knob_threading(monkeypatch):
    """--serve-kv-dtype and KUBEML_SERVE_KV_DTYPE reach the PS; an
    unknown value surfaces as a client error via the replica factory's
    ValueError -> InvalidArgsError translation (engine validates)."""
    from kubeml_tpu.cli.main import build_parser
    from kubeml_tpu.control.ps import ParameterServer

    args = build_parser().parse_args(
        ["serve", "--role", "ps", "--serve-kv-dtype", "int8"])
    assert args.serve_kv_dtype == "int8"
    monkeypatch.setenv("KUBEML_SERVE_KV_DTYPE", "int8")
    ps = ParameterServer(port=0)
    assert ps.serve_kv_dtype == "int8"
    ps2 = ParameterServer(port=0, serve_kv_dtype="f32")
    assert ps2.serve_kv_dtype == "f32"
