"""Decode-latency tests (PR 16): multi-step decode scan + speculative
decoding with a draft model.

The three new serving-path variants registered in
engine.SERVE_PATH_VARIANTS are pinned here, quoted, next to exactness
assertions (tools/check_serve_parity.py enforces this coupling):

  * 'multi_step' — the scan-over-K decode program (decode_steps > 1)
    emits K tokens per dispatch BIT-IDENTICAL to K single-step
    dispatches, across concurrent slots, mid-stream EOS, budget
    boundaries, int8 KV pages, and a weight hot-swap (which falls the
    engine back to single-step until the old generation drains).
  * 'spec_verify' — draft-proposed tokens scored by one target verify
    dispatch change NOTHING observable: emitted tokens are always the
    target model's own picks under the engine's (seed, pos) keys, so
    greedy speculation equals model.generate() and sampled speculation
    equals the plain engine exactly, at any acceptance rate.
  * 'spec_rollback' — rejected proposals roll back INSIDE the dispatch
    (the verify program re-scans from the pre-dispatch slab writing
    only accepted steps) and the host trims the over-granted pages, so
    the pager free list, refcounts, and int8 page scales end exactly
    where a never-proposed run ends.

Plus the deterministic decode-amortization proxies
(dispatches_per_token, accepted_per_dispatch — counters, never
timers): engine stat / snapshot / metric family / top line all agree.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.serving


def _nano(seed=0):
    import jax

    from kubeml_tpu.models import get_builtin
    model = get_builtin("gpt-nano")()
    module = model.module
    variables = model.init_variables(
        jax.random.PRNGKey(seed),
        {"x": np.ones((1, module.max_len), np.int32)})
    return model, module, variables


def _drive(engine, limit=10_000):
    finished = []
    while engine.active():
        finished.extend(engine.step())
        limit -= 1
        assert limit > 0, "engine failed to drain"
    return finished


# greedy + two sampled lanes; 7 new tokens is deliberately not a
# multiple of any tested K, so the budget mask trims the last window
SPECS = [([5, 6, 7], 6, 0.0, 0),
         ([9, 10, 11, 12], 8, 0.7, 1),
         ([3], 7, 1.3, 7)]


def _make(specs=SPECS, eos=None):
    from kubeml_tpu.serve.slots import GenerateRequest
    return [GenerateRequest(list(p), max_new_tokens=n, temperature=t,
                            seed=s, eos_id=eos) for p, n, t, s in specs]


def _run(module, variables, reqs, **kw):
    from kubeml_tpu.serve.engine import DecodeEngine
    eng = DecodeEngine(module, variables, slots=4, page=8,
                       prefill_chunk=8, **kw)
    for r in reqs:
        eng.attach(r)
    _drive(eng)
    return eng


def _same_tokens(reqs_a, reqs_b):
    for a, b in zip(reqs_a, reqs_b):
        np.testing.assert_array_equal(np.asarray(a.tokens),
                                      np.asarray(b.tokens))


# ------------------------------------------------------- multi-step scan

def test_multi_step_bit_identical_across_k():
    """'multi_step' K in {2, 4, 8}: K fused steps per dispatch emit the
    SAME tokens as K single-step dispatches — greedy and sampled lanes,
    concurrent slots, budgets not divisible by K — while cutting
    dispatches_per_token toward 1/K."""
    _model, module, variables = _nano()
    base_reqs = _make()
    base = _run(module, variables, base_reqs)
    for K in (2, 4, 8):
        reqs = _make()
        eng = _run(module, variables, reqs, decode_steps=K)
        assert all(r.outcome == "ok" for r in reqs)
        _same_tokens(base_reqs, reqs)
        assert eng.stats["multi_step_dispatches"] > 0
        assert eng.stats["multi_step_compiles"] == 1
        assert eng.stats["generated_tokens"] == \
            base.stats["generated_tokens"]
        # fewer program launches for the same tokens
        assert eng.stats["dispatches"] < base.stats["dispatches"]
        assert eng.dispatches_per_token < base.dispatches_per_token
        # the bytes proxy stays tied to tokens, not dispatches
        assert eng.stats["kv_bytes"] == \
            eng.stats["decode_tokens"] * eng.kv_bytes_per_token
        eng.check_pager()


def test_multi_step_mid_stream_eos_bit_identical():
    """A lane that hits EOS mid-window goes dead as DATA (masked null
    writes) — tokens still end exactly where single-step ends, and no
    pages leak from the dead lane's unused window tail."""
    _model, module, variables = _nano()
    probe = _make()
    _run(module, variables, probe)
    # pick an eos that actually appears mid-stream in the greedy lane
    eos = probe[0].tokens[2]
    base_reqs = _make(eos=eos)
    _run(module, variables, base_reqs)
    assert any(len(r.tokens) < r.max_new_tokens for r in base_reqs)
    for K in (4, 8):
        reqs = _make(eos=eos)
        eng = _run(module, variables, reqs, decode_steps=K)
        _same_tokens(base_reqs, reqs)
        eng.check_pager()


def test_multi_step_int8_kv_bit_identical():
    """'multi_step' composes with int8 KV pages: the scan body reuses
    the SAME quantize-on-write step, so tokens match single-step int8
    exactly."""
    _model, module, variables = _nano()
    base_reqs = _make()
    _run(module, variables, base_reqs, kv_dtype="int8")
    reqs = _make()
    eng = _run(module, variables, reqs, decode_steps=4, kv_dtype="int8")
    _same_tokens(base_reqs, reqs)
    assert eng.stats["multi_step_dispatches"] > 0
    eng.check_pager()


def test_multi_step_hot_swap_falls_back_bit_identical():
    """A weight hot-swap mid-flight leaves the engine multi-generation;
    the scheduler falls back to single-step until the old generation
    drains, and every stream's tokens still match the single-step
    engine running the identical attach/swap sequence."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    _m2, _mod2, variables2 = _nano(seed=1)   # genuinely different weights

    def lifecycle(**kw):
        eng = DecodeEngine(module, variables, slots=4, page=8,
                           prefill_chunk=8, **kw)
        a = GenerateRequest([5, 6, 7, 8], max_new_tokens=12,
                            temperature=0.0, seed=0)
        eng.attach(a)
        for _ in range(3):
            eng.step()
        eng.install_weights(variables2)      # a stays pinned to gen 0
        b = GenerateRequest([9, 10, 11], max_new_tokens=8,
                            temperature=0.8, seed=2)
        eng.attach(b)                        # b decodes under gen 1
        _drive(eng)
        eng.check_pager()
        return eng, [a, b]

    base_eng, base_reqs = lifecycle()
    eng, reqs = lifecycle(decode_steps=4)
    assert all(r.outcome == "ok" for r in base_reqs + reqs)
    _same_tokens(base_reqs, reqs)
    assert eng.stats["generations_retired"] >= 1
    # the swap really forced single-step work in the multi engine
    assert eng.stats["compiles"] == 1
    assert eng.stats["dispatches"] > eng.stats["multi_step_dispatches"]


def test_multi_step_program_validates():
    from kubeml_tpu.models.gpt import build_paged_multi_step_decode
    from kubeml_tpu.serve.engine import DecodeEngine

    _model, module, variables = _nano()
    with pytest.raises(ValueError, match="steps"):
        build_paged_multi_step_decode(module, 1)
    with pytest.raises(ValueError, match="decode steps"):
        DecodeEngine(module, variables, decode_steps=0)


# ------------------------------------------------- speculative decoding

def test_spec_greedy_matches_generate():
    """'spec_verify' against the model's own generate(): a self-draft
    proposes K tokens, one verify dispatch scores them, and the greedy
    stream's tokens are BIT-IDENTICAL to non-speculative KV-cache
    generation. Self-drafting also proves the acceptance upside:
    accepted_per_dispatch > 1 token per program launch."""
    model, module, variables = _nano()
    prompt = [5, 6, 7, 8]
    n_new = 12
    ref = model.generate(variables, np.asarray([prompt], np.int32),
                         max_new_tokens=n_new, temperature=0.0)
    from kubeml_tpu.serve.slots import GenerateRequest
    req = GenerateRequest(list(prompt), max_new_tokens=n_new)
    eng = _run(module, variables, [req], draft_module=module,
               draft_variables=variables)
    assert req.outcome == "ok"
    np.testing.assert_array_equal(
        np.asarray(req.tokens), np.asarray(ref[0, len(prompt):]))
    assert eng.stats["verify_dispatches"] > 0
    assert eng.stats["draft_tokens"] > 0
    # a greedy self-draft agrees with its own target: > 1 token/dispatch
    assert eng.accepted_per_dispatch > 1.0
    assert eng.dispatches_per_token < 1.0
    eng.check_pager()


def test_spec_sampled_concurrent_bit_identical():
    """Speculation never changes emitted tokens — they are ALWAYS the
    target's picks under the engine's (seed, pos) keys; the draft only
    gates how many commit per dispatch. Sampled lanes at three
    temperatures match the plain engine exactly, even under a
    deliberately disagreeing draft (different init)."""
    _model, module, variables = _nano()
    _m2, draft_mod, draft_vars = _nano(seed=3)
    base_reqs = _make()
    _run(module, variables, base_reqs)
    for dm, dv in ((module, variables), (draft_mod, draft_vars)):
        reqs = _make()
        eng = _run(module, variables, reqs, draft_module=dm,
                   draft_variables=dv)
        assert all(r.outcome == "ok" for r in reqs)
        _same_tokens(base_reqs, reqs)
        assert eng.stats["verify_dispatches"] > 0
        # counter sanity: every drafted token lands in one bucket, and
        # accepted additionally counts each window's bonus target pick
        assert eng.stats["draft_tokens"] > 0
        assert eng.stats["rejected_tokens"] <= eng.stats["draft_tokens"]
        assert eng.stats["accepted_tokens"] + \
            eng.stats["rejected_tokens"] >= eng.stats["draft_tokens"]
        eng.check_pager()


def test_spec_rollback_restores_pager_state_exactly():
    """'spec_rollback' with int8 KV: a disagreeing draft forces
    rejections every window; the verify program's second pass re-scans
    from the pre-dispatch slab writing only accepted steps, and the
    host ungrants the unused page tail. After draining, the free list
    (ORDER included), refcounts, held-page int8 payloads and per-page
    scales are EXACTLY the never-proposed engine's — and a follow-up
    stream decodes identical tokens from that state."""
    import jax.numpy as jnp

    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    _m2, draft_mod, draft_vars = _nano(seed=9)

    def run_one(**kw):
        eng = DecodeEngine(module, variables, slots=2, page=8,
                           prefill_chunk=8, kv_dtype="int8", **kw)
        # 10 prompt tokens = one FULL page (prefix-cached after
        # release) + a partial: held pages survive the drain
        req = GenerateRequest(list(range(5, 15)), max_new_tokens=14,
                              temperature=0.0, seed=0)
        eng.attach(req)
        _drive(eng)
        return eng, req

    base, base_req = run_one()
    spec, spec_req = run_one(draft_module=draft_mod,
                             draft_variables=draft_vars)
    assert spec.stats["rejected_tokens"] > 0   # rollback was exercised
    np.testing.assert_array_equal(np.asarray(base_req.tokens),
                                  np.asarray(spec_req.tokens))
    # pager state: identical free-list ORDER and identical refcounts
    assert spec.pager._free == base.pager._free
    assert spec.pager._refs == base.pager._refs
    # held pages (referenced or prefix-cached — everything not on the
    # free list) carry bit-identical int8 payloads and scales: the
    # replay pass plus trim leaves no trace of rejected writes (freed
    # pages may hold garbage; only held ones matter)
    held = sorted(set(range(1, base.geom.pages)) - set(base.pager._free))
    assert held                                 # prefix pages survive
    assert spec.slab.k.dtype == jnp.int8
    for name in ("k", "v", "k_scale", "v_scale"):
        a = np.asarray(getattr(base.slab, name))[:, held]
        b = np.asarray(getattr(spec.slab, name))[:, held]
        np.testing.assert_array_equal(a, b)
    # behavioral closure: a fresh stream on each engine (allocating out
    # of the supposedly-identical free lists) decodes identical tokens
    nxt_a = GenerateRequest([20, 21, 22], max_new_tokens=6,
                            temperature=0.9, seed=4)
    nxt_b = GenerateRequest([20, 21, 22], max_new_tokens=6,
                            temperature=0.9, seed=4)
    base.attach(nxt_a)
    spec.attach(nxt_b)
    _drive(base)
    _drive(spec)
    np.testing.assert_array_equal(np.asarray(nxt_a.tokens),
                                  np.asarray(nxt_b.tokens))
    base.check_pager()
    spec.check_pager()


def test_spec_verify_program_validates():
    from kubeml_tpu.models.gpt import build_paged_spec_verify_step
    from kubeml_tpu.serve.engine import DecodeEngine

    _model, module, variables = _nano()
    with pytest.raises(ValueError, match="draft_variables"):
        DecodeEngine(module, variables, draft_module=module)
    with pytest.raises(ValueError, match="window"):
        build_paged_spec_verify_step(module, module, 4, 1)
    with pytest.raises(ValueError, match="window"):
        build_paged_spec_verify_step(module, module, 4,
                                     module.max_len + 1)


# --------------------------------------------- program inventory pinning

def test_program_inventory_compile_counts_pinned():
    """The program inventory is EXACT and compile-once: a multi-step
    engine holds {prefill, decode, multi-step}, a speculative engine
    holds {prefill, decode, verify}, each compiled exactly once, and a
    second wave of joins/leaves adds dispatches but ZERO compiles."""
    from kubeml_tpu.serve.engine import DecodeEngine

    _model, module, variables = _nano()

    def churn(eng):
        for r in _make():
            eng.attach(r)
        _drive(eng)

    multi = DecodeEngine(module, variables, slots=4, page=8,
                         prefill_chunk=8, decode_steps=4)
    spec = DecodeEngine(module, variables, slots=4, page=8,
                        prefill_chunk=8, draft_module=module,
                        draft_variables=variables)
    for eng, extra in ((multi, "multi_step_compiles"),
                       (spec, "verify_compiles")):
        churn(eng)
        pinned = (eng.stats["compiles"], eng.stats["prefill_compiles"],
                  eng.stats[extra])
        assert pinned == (1, 1, 1)
        assert eng.compile_tracker.compiles == 3
        disp = eng.stats["dispatches"]
        churn(eng)                              # second wave: data only
        assert eng.stats["dispatches"] > disp
        assert (eng.stats["compiles"], eng.stats["prefill_compiles"],
                eng.stats[extra]) == pinned
        assert eng.compile_tracker.compiles == 3
        # every decode-lane dispatch (single, fused, verify) is tracked
        assert eng.compile_tracker.dispatches == \
            eng.stats["dispatches"] + eng.stats["prefill_dispatches"]


# ------------------------------------------- flight recorder schema v2

def test_flight_schema_v2_splits_dispatch_lanes():
    """FLIGHT_FIELDS v2 splits 'dispatches' into prefill/decode lanes
    so amortization regressions are visible per step; records sum back
    to the engine's cumulative dispatch stats."""
    from kubeml_tpu.serve.flight import (FLIGHT_FIELDS,
                                         FLIGHT_SCHEMA_VERSION)

    assert FLIGHT_SCHEMA_VERSION == 2
    assert "prefill_dispatches" in FLIGHT_FIELDS
    assert "decode_dispatches" in FLIGHT_FIELDS
    assert "dispatches" not in FLIGHT_FIELDS

    _model, module, variables = _nano()
    reqs = _make()
    eng = _run(module, variables, reqs, decode_steps=4)
    recs = eng.flight.snapshot()
    assert all(set(FLIGHT_FIELDS) <= set(r) for r in recs)
    assert sum(r["prefill_dispatches"] for r in recs) == \
        eng.stats["prefill_dispatches"]
    assert sum(r["decode_dispatches"] for r in recs) == \
        eng.stats["dispatches"]


# ------------------------------------- metrics / snapshot / CLI / knobs

def test_spec_metric_families_and_snapshot():
    """The three speculation counter families pass the metrics lint,
    the service delta-advances them from cumulative engine stats (no
    double counting), and the snapshot carries both amortization
    proxies for health/top."""
    from kubeml_tpu.metrics.prom import MetricsRegistry
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.service import ServeService
    from tools.check_metrics import validate_exposition

    m = MetricsRegistry()
    m.note_serve_draft_tokens("m1", 40)
    m.note_serve_accepted_tokens("m1", 25)
    m.note_serve_rejected_tokens("m1", 15)
    text = m.exposition()
    assert validate_exposition(text) == []
    assert 'kubeml_serve_draft_tokens_total{model="m1"} 40' in text
    assert 'kubeml_serve_accepted_tokens_total{model="m1"} 25' in text
    assert 'kubeml_serve_rejected_tokens_total{model="m1"} 15' in text
    m.clear_serve("m1")
    assert 'model="m1"' not in m.exposition()

    _model, module, variables = _nano()
    engine = DecodeEngine(module, variables, slots=2, page=8,
                          draft_module=module, draft_variables=variables)
    m2 = MetricsRegistry()
    svc = ServeService("m2", engine, max_queue=1, metrics=m2)  # no loop
    engine.stats["draft_tokens"] = 40
    engine.stats["accepted_tokens"] = 25
    engine.stats["rejected_tokens"] = 15
    engine.stats["generated_tokens"] = 40
    engine.stats["dispatches"] = 10
    engine.stats["verify_dispatches"] = 10
    svc._publish()
    svc._publish()   # same cumulative values: no double count
    text = m2.exposition()
    assert 'kubeml_serve_draft_tokens_total{model="m2"} 40' in text
    assert 'kubeml_serve_accepted_tokens_total{model="m2"} 25' in text
    assert 'kubeml_serve_rejected_tokens_total{model="m2"} 15' in text
    snap = svc.snapshot()
    assert snap["serve_dispatches_per_token"] == pytest.approx(0.25)
    assert snap["serve_accepted_per_dispatch"] == pytest.approx(2.5)


def test_top_renders_decode_amortization_line():
    from kubeml_tpu.cli.main import _render_top

    doc = {"id": "serve:m1", "state": "healthy", "reasons": [],
           "latest": {"serve_active_slots": 1, "serve_slot_cap": 4,
                      "serve_queue_depth": 0, "serve_queue_cap": 8,
                      "serve_kv_page_utilization": 0.5,
                      "serve_dispatches_per_token": 0.25,
                      "serve_accepted_per_dispatch": 3.2}}
    out = _render_top(doc)
    assert "decode amortization: 0.25 dispatches/token" in out
    assert "accepted 3.2/verify" in out
    # without a verify program the accept clause stays off the line
    doc["latest"]["serve_accepted_per_dispatch"] = 0.0
    assert "accepted" not in _render_top(doc)


def test_spec_knob_threading(monkeypatch):
    """--serve-decode-steps / --serve-draft-model and their env twins
    reach the PS; explicit constructor args win over env."""
    from kubeml_tpu.cli.main import build_parser
    from kubeml_tpu.control.ps import ParameterServer

    args = build_parser().parse_args(
        ["serve", "--role", "ps", "--serve-decode-steps", "4",
         "--serve-draft-model", "tiny-draft"])
    assert args.serve_decode_steps == 4
    assert args.serve_draft_model == "tiny-draft"
    monkeypatch.setenv("KUBEML_SERVE_DECODE_STEPS", "8")
    monkeypatch.setenv("KUBEML_SERVE_DRAFT_MODEL", "env-draft")
    ps = ParameterServer(port=0)
    assert ps.serve_decode_steps == 8
    assert ps.serve_draft_model == "env-draft"
    ps2 = ParameterServer(port=0, serve_decode_steps=2,
                          serve_draft_model="flag-draft")
    assert ps2.serve_decode_steps == 2
    assert ps2.serve_draft_model == "flag-draft"


def test_fleet_snapshot_merges_amortization_from_counters():
    """The fleet snapshot derives its ratios from SUMMED engine
    counters across replicas, not by averaging per-replica ratios."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.fleet import ServeFleet
    from kubeml_tpu.serve.service import ServeService

    _model, module, variables = _nano()

    def factory(index):
        engine = DecodeEngine(module, variables, slots=2, page=8)
        return ServeService("m1", engine, max_queue=2, supervise=False)

    fleet = ServeFleet("m1", factory, replicas_min=2, replicas_max=2,
                       autoscale_interval_s=0.0)
    fleet.start()
    try:
        engines = [svc.engine for svc in fleet._replicas.values()]
        engines[0].stats.update(dispatches=4, generated_tokens=16,
                                accepted_tokens=12, verify_dispatches=4)
        engines[1].stats.update(dispatches=6, generated_tokens=4,
                                accepted_tokens=0, verify_dispatches=0)
        snap = fleet.snapshot()
        # 10 dispatches / 20 tokens — NOT mean(0.25, 1.5)
        assert snap["serve_dispatches_per_token"] == pytest.approx(0.5)
        assert snap["serve_accepted_per_dispatch"] == pytest.approx(3.0)
    finally:
        fleet.stop()
