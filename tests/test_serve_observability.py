"""Serving-plane observability tests (tracing + flight recorder + TTFT).

The contracts pinned here:

  * span-tree exactness — one /generate request yields queue_wait ->
    admit -> prefill_chunk per chunk -> a first_token instant ->
    sampled decode spans -> one terminal instant, all parented to the
    request's root span, timestamped on the engine clock (fake-clock
    verified to the tick)
  * TTFT attribution — queue + prefill + interleave == TTFT exactly
    (interleave is the remainder by construction), both in the trace
    args and in the kubeml_serve_ttft_breakdown_seconds histograms
  * flight recorder — always-on fixed-size ring, O(1) per step, decode
    output bit-identical with it (and tracing) on or off; wraparound
    keeps the newest records oldest-first; auto-snapshot on shed onset
  * trace plumbing — client X-KubeML-Trace-Id rides every span of its
    request through the merged GET /trace?id=serve:<model> document;
    serving-sink drops land in kubeml_trace_events_dropped_total under
    the serve pseudo-job id and in the merge metadata
  * lint — tools/check_serve_spans.py holds every SERVE_SPAN_KINDS name
    to a quoted assertion in tests/ (this file carries them)
"""

import itertools
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

pytestmark = pytest.mark.serving


def _nano():
    import jax

    from kubeml_tpu.models import get_builtin
    model = get_builtin("gpt-nano")()
    module = model.module
    variables = model.init_variables(
        jax.random.PRNGKey(0),
        {"x": np.ones((1, module.max_len), np.int32)})
    return model, module, variables


def _drive(engine, limit=10_000):
    finished = []
    while engine.active():
        finished.extend(engine.step())
        limit -= 1
        assert limit > 0, "engine failed to drain"
    return finished


def _fake_clock():
    """Deterministic clock: each call is one second after the last, so
    every span endpoint is an exact integer and the additive-breakdown
    arithmetic has no float slop to hide behind."""
    counter = itertools.count(1)
    return lambda: float(next(counter))


def _by_name(events, name):
    return [e for e in events if e["name"] == name]


# ------------------------------------------------------------ flight ring

def test_flight_ring_wraparound_keeps_newest_oldest_first():
    from kubeml_tpu.serve.flight import FlightRecorder

    fl = FlightRecorder(capacity=4)
    assert len(fl) == 0 and fl.total == 0 and fl.snapshot() == []
    for i in range(10):
        fl.record({"step": i})
    assert fl.total == 10
    assert len(fl) == 4
    assert [r["step"] for r in fl.snapshot()] == [6, 7, 8, 9]
    # snapshot returns copies: mutating them never corrupts the ring
    fl.snapshot()[0]["step"] = -1
    assert [r["step"] for r in fl.snapshot()] == [6, 7, 8, 9]
    with pytest.raises(ValueError):
        FlightRecorder(capacity=0)


def test_flight_records_schema_and_kinds():
    """Every engine step — prefill, decode, idle — leaves exactly one
    record with the documented field set."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.flight import FLIGHT_FIELDS
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    engine = DecodeEngine(module, variables, slots=2, page=8,
                          prefill_chunk=16)
    req = GenerateRequest(list(range(2, 36)), max_new_tokens=4)
    engine.attach(req)
    _drive(engine)
    engine.step()  # idle step records too
    records = engine.flight.snapshot()
    assert len(records) == engine.flight.total
    for rec in records:
        assert set(rec) == set(FLIGHT_FIELDS)
    kinds = {r["kind"] for r in records}
    assert "prefill" in kinds and "decode" in kinds and "idle" in kinds
    steps = [r["step"] for r in records]
    assert steps == sorted(steps)  # oldest first, monotone


def test_decode_bit_identical_with_recorder_and_tracer_on_or_off():
    """The observability plane is host-side only: identical tokens with
    the flight recorder + tracer enabled and with both disabled."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest
    from kubeml_tpu.utils.trace import Tracer

    _model, module, variables = _nano()
    specs = [([5, 6, 7], 6, 0.0, 0),
             ([9, 10, 11, 12], 8, 0.7, 1)]

    def run(**kw):
        engine = DecodeEngine(module, variables, slots=4, page=4, **kw)
        reqs = [GenerateRequest(list(p), max_new_tokens=n, temperature=t,
                                seed=s) for p, n, t, s in specs]
        for r in reqs:
            engine.attach(r)
        _drive(engine)
        return [r.tokens for r in reqs]

    instrumented = run(tracer=Tracer(), flight_steps=8,
                       decode_span_every=1)
    bare = run(tracer=None, flight_steps=0)
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(t) for t in instrumented]),
        np.concatenate([np.asarray(t) for t in bare]))


# -------------------------------------------------------- span-tree shape

def test_request_span_tree_exact_under_fake_clock():
    """One chunked-prefill request's full tree, to the tick: the fake
    clock advances 1s per reading, so every duration and the additive
    TTFT identity are exact."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest
    from kubeml_tpu.utils.trace import Tracer

    _model, module, variables = _nano()
    clk = _fake_clock()
    tracer = Tracer(clock=clk)
    engine = DecodeEngine(module, variables, slots=2, page=8, clock=clk,
                          prefill_chunk=16, tracer=tracer,
                          decode_span_every=2)
    prompt = list(range(2, 42))  # 40 tokens -> chunks of 16, 16, 7
    req = GenerateRequest(prompt, max_new_tokens=6,
                          trace_id="cafecafe00000001")
    req.submitted_at = clk()  # what ServeService.submit records
    engine.attach(req)
    _drive(engine)
    assert req.outcome == "ok" and len(req.tokens) == 6
    events = tracer.events()

    # every span/instant of the tree carries the request's trace_id and
    # parents to the root "generate" span
    for ev in events:
        assert ev["args"]["trace_id"] == "cafecafe00000001"
        assert ev["args"]["parent"] == "generate"
        assert ev["args"]["rid"] == req.rid

    (qw,) = _by_name(events, "queue_wait")
    assert qw["ph"] == "X"
    assert qw["ts"] == round(req.submitted_at * 1e6)
    (admit,) = _by_name(events, "admit")
    assert admit["args"]["prompt_tokens"] == 40
    assert admit["ts"] == qw["ts"] + qw["dur"]  # queue ends where admit starts
    chunks = _by_name(events, "prefill_chunk")
    assert [c["args"]["tokens"] for c in chunks] == [16, 16, 7]
    assert all(c["dur"] > 0 for c in chunks)
    (ft,) = _by_name(events, "first_token")
    assert ft["ph"] == "i"
    decodes = _by_name(events, "decode")
    assert [d["args"]["token_index"] for d in decodes] == [2, 4, 6]
    (fin,) = _by_name(events, "finish")
    assert fin["args"]["outcome"] == "ok" and fin["args"]["tokens"] == 6
    assert fin["ts"] == round(req.finished_at * 1e6)

    # additive TTFT attribution: queue + prefill + interleave == TTFT,
    # and the components match the timeline they claim to decompose
    bd = req.ttft_breakdown
    ttft = ft["args"]["ttft"]
    assert ttft == req.first_token_at - req.submitted_at
    assert bd["queue"] == req.admitted_at - req.submitted_at
    # prefill-compute = the three chunk dispatches + the first-token
    # decode dispatch (it consumes the last prompt position); under
    # this clock every dispatch is exactly one tick, so any decode
    # span's dur stands in for the first-token dispatch's
    assert bd["prefill"] * 1e6 == pytest.approx(
        sum(c["dur"] for c in chunks) + decodes[0]["dur"], abs=1)
    assert bd["queue"] + bd["prefill"] + bd["interleave"] == \
        pytest.approx(ttft, abs=1e-9)
    assert ft["args"]["queue"] == bd["queue"]


def test_engine_cancel_and_shed_emit_terminal_instants():
    """'cancel' on mid-stream cancellation; 'shed' (not 'finish') when
    KV exhaustion sheds the newest stream."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest
    from kubeml_tpu.utils.trace import Tracer

    _model, module, variables = _nano()
    tracer = Tracer()
    engine = DecodeEngine(module, variables, slots=2, page=8,
                          prefill_chunk=0, tracer=tracer)
    req = GenerateRequest([5, 6, 7], max_new_tokens=30)
    engine.attach(req)
    engine.step()
    req.cancel()
    engine.step()
    assert req.outcome == "cancelled"
    (c,) = _by_name(tracer.events(), "cancel")
    assert c["args"]["outcome"] == "cancelled"

    # 2 usable pages of 4 tokens, each request needing 2: the newest
    # stream stalls on page exhaustion and sheds
    from kubeml_tpu.serve.pager import PageGeometry
    tracer2 = Tracer()
    tight = DecodeEngine(module, variables,
                         geom=PageGeometry(slots=2, page=4, pages=3,
                                           pages_per_slot=2),
                         tracer=tracer2)
    old = GenerateRequest([5, 6, 7, 8], max_new_tokens=4)
    new = GenerateRequest([9, 10, 11, 12], max_new_tokens=4)
    tight.attach(old)
    tight.attach(new)
    _drive(tight)
    assert new.outcome == "error" and "shed" in new.error
    sheds = _by_name(tracer2.events(), "shed")
    assert len(sheds) == 1 and sheds[0]["args"]["rid"] == new.rid
    flight_kinds = [r["kind"] for r in tight.flight.snapshot()]
    assert "shed" in flight_kinds


# ----------------------------------------------- service-level incidents

def test_shed_onset_snapshots_flight_ring_once_per_episode():
    """Admission saturation: the FIRST shed dumps the flight ring into
    the trace; sustained shedding does not re-snapshot until a publish
    pass with no sheds re-arms the episode."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.service import ServeService
    from kubeml_tpu.serve.slots import ServeSaturated
    from kubeml_tpu.utils.trace import Tracer

    _model, module, variables = _nano()
    engine = DecodeEngine(module, variables, slots=2, page=8)
    tracer = Tracer()
    svc = ServeService("m", engine, max_queue=0, tracer=tracer)
    # loop thread NOT started: submissions sit pending, so capacity
    # (slots 2 + queue 0) saturates deterministically
    svc.submit([5, 6, 7], max_new_tokens=2)
    svc.submit([8, 9], max_new_tokens=2)
    for _ in range(3):
        with pytest.raises(ServeSaturated):
            svc.submit([1, 2], max_new_tokens=2)
    events = tracer.events()
    assert len(_by_name(events, "shed")) == 3
    snaps = _by_name(events, "flight_snapshot")
    assert len(snaps) == 1  # onset only, not per shed
    assert snaps[0]["args"]["reason"] == "shed_onset"
    assert snaps[0]["args"]["total_steps"] == engine.flight.total

    svc._publish()  # sheds happened since last pass: episode stays hot
    with pytest.raises(ServeSaturated):
        svc.submit([1, 2], max_new_tokens=2)
    assert len(_by_name(tracer.events(), "flight_snapshot")) == 1
    svc._publish()  # shed-free pass? no — one shed above keeps it hot
    svc._publish()  # now a clean pass re-arms
    with pytest.raises(ServeSaturated):
        svc.submit([1, 2], max_new_tokens=2)
    assert len(_by_name(tracer.events(), "flight_snapshot")) == 2


def test_serve_trace_drops_counted_and_merged(tmp_home):
    """Serving-sink drops reach kubeml_trace_events_dropped_total under
    the serve pseudo-job id, and the merged trace metadata reports the
    timeline as partial."""
    from kubeml_tpu.metrics.prom import MetricsRegistry
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.service import ServeService
    from kubeml_tpu.serve.slots import ServeSaturated
    from kubeml_tpu.utils.trace import TraceSink, Tracer, merge_job_trace

    _model, module, variables = _nano()
    engine = DecodeEngine(module, variables, slots=1, page=8)
    reg = MetricsRegistry()
    tracer = Tracer(max_events=2)
    svc = ServeService("m", engine, max_queue=0, metrics=reg,
                       tracer=tracer,
                       trace_sink=TraceSink("serve:m", "serve"))
    svc.submit([5, 6], max_new_tokens=2)
    for _ in range(4):  # shed + snapshot fill the 2-event cap; rest drop
        with pytest.raises(ServeSaturated):
            svc.submit([1, 2], max_new_tokens=2)
    assert tracer.dropped_events > 0
    svc._publish()
    text = reg.exposition()
    assert (f'kubeml_trace_events_dropped_total{{jobid="serve:m"}} '
            f"{float(tracer.dropped_events)}") in text
    svc._flush_trace(force=True)
    merged = merge_job_trace("serve:m")
    assert merged["metadata"]["dropped_events"] == tracer.dropped_events


# ------------------------------------------------------------ end to end

@pytest.fixture()
def serve_ps(tmp_home):
    from kubeml_tpu.control.ps import ParameterServer
    from kubeml_tpu.train.checkpoint import save_checkpoint

    model, _module, variables = _nano()
    save_checkpoint("obsnano", variables,
                    {"model": "gpt-nano", "function": "gpt-nano",
                     "parallelism": 1, "epoch": 0})
    ps = ParameterServer(serve_slots=2, serve_queue_depth=1)
    ps.start()
    yield ps, model, variables
    ps.stop()


def _post(url, body, timeout=60.0, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json", **(headers or {})})
    return urllib.request.urlopen(req, timeout=timeout)


def _get_json(url, timeout=30.0):
    return json.loads(urllib.request.urlopen(url, timeout=timeout).read())


def test_trace_id_propagates_to_merged_serve_trace(serve_ps):
    """A chunked-prefill request over real HTTP with a client-minted
    trace id: the response echoes the id, and the merged serve trace
    carries the full span tree under it, with the TTFT breakdown
    summing to the TTFT."""
    from kubeml_tpu.utils.trace import TRACE_HEADER

    ps, _model, _variables = serve_ps
    tid = "feedbeef00000042"
    prompt = list(range(2, 42))  # 40 tokens -> 3 chunks at chunk=16
    resp = _post(f"{ps.url}/generate",
                 {"model_id": "obsnano", "prompt": prompt,
                  "max_new_tokens": 4},
                 headers={TRACE_HEADER: tid})
    assert resp.headers.get(TRACE_HEADER) == tid
    events = [json.loads(line) for line in resp.read().splitlines()]
    assert "done" in events[-1]

    # the serve loop flushes the sink on its publish cadence: poll the
    # merged document until this request's spans land
    deadline = time.time() + 15
    mine = []
    while time.time() < deadline:
        try:
            doc = _get_json(f"{ps.url}/trace?id=serve:obsnano")
        except urllib.error.HTTPError:
            time.sleep(0.05)
            continue
        mine = [e for e in doc["traceEvents"]
                if e.get("args", {}).get("trace_id") == tid]
        if any(e["name"] == "generate" for e in mine):
            break
        time.sleep(0.05)
    assert tid in doc["metadata"]["trace_ids"]
    names = [e["name"] for e in mine]
    assert names.count("generate") == 1
    assert "queue_wait" in names and "admit" in names
    assert names.count("prefill_chunk") >= 2
    assert "first_token" in names and "finish" in names
    (ft,) = [e for e in mine if e["name"] == "first_token"]
    bd_sum = (ft["args"]["queue"] + ft["args"]["prefill"]
              + ft["args"]["interleave"])
    assert bd_sum == pytest.approx(ft["args"]["ttft"], abs=1e-6)
    # the root brackets the whole request
    (root,) = [e for e in mine if e["name"] == "generate"]
    assert root["ts"] <= ft["ts"] <= root["ts"] + root["dur"]

    # a second client id lands in the SAME merged doc alongside
    resp = _post(f"{ps.url}/generate",
                 {"model_id": "obsnano", "prompt": [5, 6, 7],
                  "max_new_tokens": 2, "stream": False},
                 headers={TRACE_HEADER: "feedbeef00000043"})
    assert resp.headers.get(TRACE_HEADER) == "feedbeef00000043"
    assert json.loads(resp.read())["tokens"]
    deadline = time.time() + 15
    while time.time() < deadline:
        doc = _get_json(f"{ps.url}/trace?id=serve:obsnano")
        if "feedbeef00000043" in doc["metadata"]["trace_ids"]:
            break
        time.sleep(0.05)
    assert set(doc["metadata"]["trace_ids"]) >= {tid, "feedbeef00000043"}


def test_flight_endpoint_and_breakdown_exposition(serve_ps):
    """GET /flight drains the live ring; the TTFT-breakdown and
    stream-duration histogram families pass the exposition lint."""
    from tools.check_metrics import validate_exposition

    from kubeml_tpu.serve.flight import FLIGHT_FIELDS

    ps, _model, _variables = serve_ps
    _post(f"{ps.url}/generate",
          {"model_id": "obsnano", "prompt": [5, 6, 7, 8],
           "max_new_tokens": 4}).read()
    doc = _get_json(f"{ps.url}/flight?id=serve:obsnano")
    assert doc["id"] == "serve:obsnano" and doc["model"] == "obsnano"
    assert doc["capacity"] > 0
    assert doc["total_steps"] >= 1 and doc["records"]
    # the fleet router stamps each record with the replica it came from
    assert doc["replicas"] == [0]
    for rec in doc["records"]:
        assert set(rec) == set(FLIGHT_FIELDS) | {"replica"}
        assert rec["replica"] == 0
    # bare model id resolves too
    assert _get_json(f"{ps.url}/flight?id=obsnano")["id"] == \
        "serve:obsnano"
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"{ps.url}/flight?id=serve:nosuch")
    assert ei.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"{ps.url}/flight")
    assert ei.value.code == 400

    wanted = ("kubeml_serve_ttft_breakdown_seconds",
              "kubeml_serve_stream_duration_seconds")
    # the families expose immediately; the breakdown SAMPLES land when
    # the serve loop observes the finished request — poll for those
    deadline = time.time() + 10
    while time.time() < deadline:
        text = urllib.request.urlopen(f"{ps.url}/metrics").read().decode()
        if 'component="queue"' in text:
            break
        time.sleep(0.05)
    for family in wanted:
        assert f"# TYPE {family}" in text, family
    assert 'component="queue"' in text
    assert 'component="prefill"' in text
    assert 'component="interleave"' in text
    assert validate_exposition(text) == []

    # health snapshot carries the breakdown means for `kubeml top`
    deadline = time.time() + 10
    while time.time() < deadline:
        doc = _get_json(f"{ps.url}/health?id=serve:obsnano")
        if doc.get("latest", {}).get("serve_ttft_queue_s") is not None:
            break
        time.sleep(0.05)
    latest = doc["latest"]
    for field in ("serve_ttft_queue_s", "serve_ttft_prefill_s",
                  "serve_ttft_interleave_s"):
        assert field in latest
    assert latest["serve_ttft_queue_s"] + latest["serve_ttft_prefill_s"] \
        + latest["serve_ttft_interleave_s"] == \
        pytest.approx(latest["serve_ttft_p50"], rel=0.5, abs=0.05)


def test_top_renders_ttft_breakdown_line():
    from kubeml_tpu.cli.main import _render_top

    out = _render_top({
        "id": "serve:m", "state": "healthy", "reasons": [],
        "latest": {"serve_active_slots": 1, "serve_slot_cap": 2,
                   "serve_queue_depth": 0, "serve_queue_cap": 4,
                   "serve_kv_page_utilization": 0.25,
                   "serve_ttft_p50": 0.030, "serve_ttft_p99": 0.090,
                   "serve_rejected_total": 0,
                   "serve_prefill_backlog_tokens": 0,
                   "serve_prefix_hit_pct": 50.0,
                   "serve_ttft_queue_s": 0.010,
                   "serve_ttft_prefill_s": 0.015,
                   "serve_ttft_interleave_s": 0.005}})
    assert "ttft breakdown: queue 10ms  prefill 15ms  interleave 5ms" \
        in out
    # without breakdown fields the serve pane renders without the line
    out = _render_top({"id": "serve:m", "state": "healthy", "reasons": [],
                       "latest": {"serve_slot_cap": 2}})
    assert "ttft breakdown" not in out


# ------------------------------------------------------------------- lint

def test_serve_span_lint_passes_on_this_repo():
    import tools.check_serve_spans as lint
    assert lint.main(["check_serve_spans.py"]) == 0


def test_serve_span_lint_self_test(tmp_path):
    """The lint catches an unasserted kind, accepts a quoted assert
    line, and ignores names that only appear in comments."""
    import tools.check_serve_spans as lint

    root = tmp_path
    (root / "kubeml_tpu" / "serve").mkdir(parents=True)
    (root / "tests").mkdir()
    eng = root / "kubeml_tpu" / "serve" / "engine.py"
    eng.write_text('SERVE_SPAN_KINDS = ("zz_alpha", "zz_beta")\n')

    # nothing asserted -> both missing, exit 1
    assert lint.main(["x", str(root)]) == 1
    assert lint.unasserted_kinds(str(eng), str(root / "tests")) == \
        ["zz_alpha", "zz_beta"]

    # a comment mention and a non-assert use do NOT count
    t = root / "tests" / "test_spans.py"
    t.write_text('# zz_alpha is great\nkinds = ["zz_alpha"]\n'
                 'assert "zz_beta" in kinds\n')
    assert lint.unasserted_kinds(str(eng), str(root / "tests")) == \
        ["zz_alpha"]
    assert lint.main(["x", str(root)]) == 1

    # a quoted name on an assert line satisfies the lint
    t.write_text('kinds = ["zz_alpha", "zz_beta"]\n'
                 'assert "zz_alpha" in kinds\n'
                 'assert "zz_beta" in kinds\n')
    assert lint.main(["x", str(root)]) == 0

    # a miswired tuple (engine refactor) fails loudly, not silently
    eng.write_text("RENAMED = ()\n")
    assert lint.main(["x", str(root)]) == 1
