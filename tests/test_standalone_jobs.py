"""Standalone (process-per-job) mode: PS spawns a jobserver child process
and speaks the reference's per-job REST surface to it.

Mirrors the reference's STANDALONE_JOBS=true pod-per-job deployment
(ml/pkg/ps/job_pod.go + ml/pkg/train/api.go:141-149): job in its own
process, /start pushed with retries after readiness, scheduler updates
relayed through PS POST /update/{jobId} -> job POST /update, metric and
finish notifications flowing back over HTTP.
"""

import time

import numpy as np
import pytest

from kubeml_tpu.api.errors import KubeMLException
from kubeml_tpu.api.types import TrainOptions, TrainRequest
from kubeml_tpu.control.client import KubemlClient
from kubeml_tpu.control.deployment import start_deployment

from tests.test_control_plane import wait_history, write_blob_files


@pytest.fixture()
def standalone_stack(tmp_path, tmp_home, mesh8, monkeypatch):
    monkeypatch.setenv("STANDALONE_JOBS", "true")
    dep = start_deployment(mesh=mesh8)
    assert dep.ps.standalone_jobs
    client = KubemlClient(dep.controller_url)
    yield dep, client, tmp_path
    dep.stop()


def test_standalone_train_updates_and_infer(standalone_stack):
    dep, client, tmp_path = standalone_stack
    paths = write_blob_files(tmp_path)
    client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])

    # dynamic parallelism: exercises the full relay chain
    # child -> scheduler /job -> PS /update/{jobId} -> child /update
    req = TrainRequest(model_type="mlp", batch_size=32, epochs=3,
                       dataset="blobs", lr=0.1,
                       options=TrainOptions(default_parallelism=2, k=2))
    job_id = client.v1().networks().train(req)

    # the job must be running as a child process, not a thread (records
    # are reserved before the spawn, so wait for the url to be set)
    deadline = time.time() + 180
    rec = None
    while time.time() < deadline:
        with dep.ps._jobs_lock:
            rec = dep.ps.jobs.get(job_id)
        if rec is not None and rec.url is not None:
            break
        time.sleep(0.2)
    assert rec is not None, "job record never appeared"
    assert rec.proc is not None and rec.url is not None
    assert rec.thread is None and rec.job is None

    history = wait_history(client, job_id, timeout=240)
    assert len(history.data.train_loss) == 3
    # throughput policy always scales up on the second decision
    assert history.data.parallelism[0] == 2
    assert history.data.parallelism[1] >= 2

    # child process reaped after finish; metrics series cleared
    assert dep.ps.wait_for_job(job_id, timeout=30)
    assert f'jobid="{job_id}"' not in dep.ps.metrics.exposition()

    # inference from the checkpoint written by the CHILD process
    x = np.load(paths["xte"])[:5]
    preds = client.v1().networks().infer(job_id, x.tolist())
    assert len(preds) == 5


def test_standalone_stop(standalone_stack):
    dep, client, tmp_path = standalone_stack
    paths = write_blob_files(tmp_path)
    client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])

    req = TrainRequest(model_type="mlp", batch_size=16, epochs=500,
                       dataset="blobs", lr=0.05,
                       options=TrainOptions(default_parallelism=2,
                                            static_parallelism=True, k=1))
    job_id = client.v1().networks().train(req)

    # wait until it is actually training, then stop through the controller
    deadline = time.time() + 180
    while time.time() < deadline:
        tasks = client.v1().tasks().list()
        if any(t.job_id == job_id and t.state == "running" for t in tasks):
            break
        time.sleep(0.3)
    client.v1().tasks().stop(job_id)

    assert dep.ps.wait_for_job(job_id, timeout=240), "job did not stop"
    # a stopped job still records its partial history (job.go:250-260)
    history = wait_history(client, job_id, timeout=60)
    assert len(history.data.train_loss) < 500
