"""Standalone (process-per-job) mode: PS spawns a jobserver child process
and speaks the reference's per-job REST surface to it.

Mirrors the reference's STANDALONE_JOBS=true pod-per-job deployment
(ml/pkg/ps/job_pod.go + ml/pkg/train/api.go:141-149): job in its own
process, /start pushed with retries after readiness, scheduler updates
relayed through PS POST /update/{jobId} -> job POST /update, metric and
finish notifications flowing back over HTTP.
"""

import time

import numpy as np
import pytest

from kubeml_tpu.api.errors import KubeMLException
from kubeml_tpu.api.types import TrainOptions, TrainRequest
from kubeml_tpu.control.client import KubemlClient
from kubeml_tpu.control.deployment import start_deployment

from tests.test_control_plane import wait_history, write_blob_files


@pytest.fixture()
def standalone_stack(tmp_path, tmp_home, mesh8, monkeypatch):
    monkeypatch.setenv("STANDALONE_JOBS", "true")
    # CI runs many JAX processes concurrently; a child's import/init can
    # exceed the 120 s production default, which would fail the start
    # (or eat a chaos test's restart budget) spuriously
    monkeypatch.setenv("KUBEML_JOB_START_TIMEOUT", "600")
    dep = start_deployment(mesh=mesh8)
    assert dep.ps.standalone_jobs
    client = KubemlClient(dep.controller_url)
    yield dep, client, tmp_path
    dep.stop()


def test_standalone_train_updates_and_infer(standalone_stack):
    dep, client, tmp_path = standalone_stack
    paths = write_blob_files(tmp_path)
    client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])

    # dynamic parallelism: exercises the full relay chain
    # child -> scheduler /job -> PS /update/{jobId} -> child /update
    req = TrainRequest(model_type="mlp", batch_size=32, epochs=3,
                       dataset="blobs", lr=0.1,
                       options=TrainOptions(default_parallelism=2, k=2))
    trace_id = "feed0123beef4567"
    job_id = client.v1().networks().train(req, trace_id=trace_id)

    # the job must be running as a child process, not a thread (records
    # are reserved before the spawn, so wait for the url to be set)
    deadline = time.time() + 180
    rec = None
    while time.time() < deadline:
        with dep.ps._jobs_lock:
            rec = dep.ps.jobs.get(job_id)
        if rec is not None and rec.url is not None:
            break
        time.sleep(0.2)
    assert rec is not None, "job record never appeared"
    assert rec.proc is not None and rec.url is not None
    assert rec.thread is None and rec.job is None

    history = wait_history(client, job_id, timeout=240)
    assert len(history.data.train_loss) == 3
    # throughput policy always scales up on the second decision
    assert history.data.parallelism[0] == 2
    assert history.data.parallelism[1] >= 2

    # child process reaped after finish; metrics series cleared
    assert dep.ps.wait_for_job(job_id, timeout=30)
    assert f'jobid="{job_id}"' not in dep.ps.metrics.exposition()

    # cross-process trace correlation: the client-minted trace id
    # appears in spans recorded by the standalone CHILD process (its
    # trace file is pid-suffixed with the child's pid, not ours)
    import os
    from kubeml_tpu.utils.trace import merge_job_trace
    doc = merge_job_trace(job_id)
    assert doc["metadata"]["trace_ids"] == [trace_id]
    child_pids = {int(s.split("-")[1].split(".")[0])
                  for s in doc["metadata"]["sources"]
                  if s.startswith("job-")}
    assert child_pids and os.getpid() not in child_pids
    epochs = [e for e in doc["traceEvents"]
              if e.get("ph") == "X" and e["name"] == "epoch"]
    assert len(epochs) == 3
    assert all(e["args"]["trace_id"] == trace_id
               and e["pid"] in child_pids for e in epochs)

    # inference from the checkpoint written by the CHILD process
    x = np.load(paths["xte"])[:5]
    preds = client.v1().networks().infer(job_id, x.tolist())
    assert len(preds) == 5


def test_standalone_stop(standalone_stack):
    dep, client, tmp_path = standalone_stack
    paths = write_blob_files(tmp_path)
    client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])

    req = TrainRequest(model_type="mlp", batch_size=16, epochs=500,
                       dataset="blobs", lr=0.05,
                       options=TrainOptions(default_parallelism=2,
                                            static_parallelism=True, k=1))
    job_id = client.v1().networks().train(req)

    # wait until it is actually training, then stop through the controller
    deadline = time.time() + 180
    while time.time() < deadline:
        tasks = client.v1().tasks().list()
        if any(t.job_id == job_id and t.state == "running" for t in tasks):
            break
        time.sleep(0.3)
    client.v1().tasks().stop(job_id)

    assert dep.ps.wait_for_job(job_id, timeout=240), "job did not stop"
    # a stopped job still records its partial history (job.go:250-260)
    history = wait_history(client, job_id, timeout=60)
    assert len(history.data.train_loss) < 500


@pytest.fixture()
def partitioned_stack(tmp_path, tmp_home, monkeypatch):
    """Standalone PS with TWO device-partition slots, each exposing its
    own 2-virtual-CPU-device view to the job process (the single-chip
    stand-in for per-job TPU_VISIBLE_DEVICES pinning)."""
    from kubeml_tpu.testing import virtual_cpu_env
    dep = start_deployment(mesh=None, standalone_jobs=True,
                           job_partitions=[virtual_cpu_env(2),
                                           virtual_cpu_env(2)])
    client = KubemlClient(dep.controller_url)
    yield dep, client, tmp_path
    dep.stop()


def test_dual_standalone_jobs_with_partitions(partitioned_stack):
    """Two CONCURRENT standalone jobs, each leasing its own device
    partition (distinct slots while running); a third submission while
    both slots are leased is refused 503; slots free after the
    processes exit and a new job starts (VERDICT r1 item 10)."""
    from kubeml_tpu.api.types import TrainTask
    from kubeml_tpu.control.httpd import http_json

    dep, client, tmp_path = partitioned_stack
    paths = write_blob_files(tmp_path, n_train=2000)
    client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])

    req = TrainRequest(model_type="mlp", batch_size=16, epochs=4,
                       dataset="blobs", lr=0.05,
                       options=TrainOptions(default_parallelism=2, k=1,
                                            static_parallelism=True))
    ids = [client.v1().networks().train(req) for _ in range(2)]

    # both running as processes, each holding a DIFFERENT partition
    deadline = time.time() + 240
    held = {}
    while time.time() < deadline and len(held) < 2:
        with dep.ps._jobs_lock:
            for jid in ids:
                rec = dep.ps.jobs.get(jid)
                if rec is not None and rec.partition is not None:
                    held[jid] = rec.partition
        time.sleep(0.1)
    assert sorted(held.values()) == [0, 1], held

    # a direct /start while both slots are leased: PS refuses 503
    extra = TrainTask(job_id="overflow1", parameters=req, parallelism=2)
    with pytest.raises(KubeMLException) as ei:
        http_json("POST", dep.ps.url + "/start", extra.to_dict())
    assert ei.value.status_code == 503

    # ... while the PRODUCT path does not lose the job: the scheduler
    # requeues on 503 and starts it once a slot frees
    third = client.v1().networks().train(req)

    for jid in ids:
        h = wait_history(client, jid, timeout=300)
        assert len(h.data.train_loss) == 4
        assert h.data.train_loss[-1] < h.data.train_loss[0]
    h = wait_history(client, third, timeout=300)
    assert len(h.data.train_loss) == 4
    for jid in ids + [third]:
        dep.ps.wait_for_job(jid)

    # every slot released once the processes are gone
    deadline = time.time() + 60
    while time.time() < deadline and dep.ps._busy_partitions:
        time.sleep(0.1)
    assert not dep.ps._busy_partitions


def test_crashed_job_process_releases_partition(partitioned_stack):
    """A child that dies WITHOUT posting /finish (OOM-kill, segfault)
    must not pin its record or its device partition: the PS watchdog
    reaps it and frees the slot."""
    dep, client, tmp_path = partitioned_stack
    paths = write_blob_files(tmp_path, n_train=4000)
    client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])
    req = TrainRequest(model_type="mlp", batch_size=16, epochs=50,
                       dataset="blobs", lr=0.05,
                       options=TrainOptions(default_parallelism=2, k=1,
                                            static_parallelism=True,
                                            max_restarts=0))
    job_id = client.v1().networks().train(req)
    deadline = time.time() + 240
    rec = None
    while time.time() < deadline:
        with dep.ps._jobs_lock:
            rec = dep.ps.jobs.get(job_id)
        if rec is not None and rec.url is not None:
            break
        time.sleep(0.1)
    assert rec is not None and rec.partition is not None
    rec.proc.kill()  # simulated OOM-kill; max_restarts=0 => must NOT respawn
    deadline = time.time() + 60
    while time.time() < deadline:
        with dep.ps._jobs_lock:
            gone = job_id not in dep.ps.jobs
        if gone and not dep.ps._busy_partitions:
            break
        time.sleep(0.1)
    assert gone
    assert not dep.ps._busy_partitions


# ------------------------------------------- crash-injection machinery
#
# Shared by the recovery chaos tests below. Kill windows are kept tens
# of seconds wide through n_train sizing (~1 s/epoch x tens of epochs):
# at 0.2 s/epoch the job could finish before a load-starved poll thread
# landed the kill (measured flaky under a concurrent full-tier run).


def _read_manifest(tmp_home, job_id) -> dict:
    import json
    import os
    try:
        with open(os.path.join(str(tmp_home), "models", job_id,
                               "manifest.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _kill_in_window(dep, tmp_home, job_id, epochs, expect_restarts=0,
                    timeout=240.0, min_epoch=1, sig=None):
    """Wait for the job's incarnation `expect_restarts` to be fully
    RUNNING (task state 'running' — a kill between readiness and the
    /start push would hit a child that never received its task) with a
    durable MID-JOB checkpoint (min_epoch <= manifest epoch < epochs),
    then SIGKILL it (or send `sig`, e.g. SIGTERM for the preemption
    grace path). min_epoch > 1 lets chained-crash tests require the
    CURRENT incarnation to have checkpointed (not just the previous
    one's leftover manifest). Returns the record."""
    deadline = time.time() + timeout
    seen = False
    while time.time() < deadline:
        with dep.ps._jobs_lock:
            rec = dep.ps.jobs.get(job_id)
        if rec is None:
            # BEFORE the job ever registered this is just the scheduler's
            # asynchronous dispatch not having run yet (the queue loop
            # picks the task moments after submit — a fast poll can beat
            # it); AFTER it registered, a vanished record means the job
            # ended and the test's premise is broken
            assert not seen, "job ended before the kill window"
            time.sleep(0.05)
            continue
        seen = True
        if rec.restarts == expect_restarts and rec.proc is not None \
                and rec.url is not None \
                and rec.task.state == "running" and \
                min_epoch <= _read_manifest(tmp_home, job_id
                                            ).get("epoch", 0) < epochs:
            if sig is None:
                rec.proc.kill()
            else:
                rec.proc.send_signal(sig)
            return rec
        time.sleep(0.05)
    raise AssertionError("kill window never opened")


def test_crashed_job_restarts_from_checkpoint(standalone_stack, tmp_home):
    """Checkpoint-based crash recovery (VERDICT r3 item 2): SIGKILL the
    standalone job process mid-job, after at least one periodic
    checkpoint is durable. The PS watchdog must respawn it with
    resume_from = its own job id; the restarted process restores the
    completed epochs' history from the checkpoint manifest and runs the
    job to completion — one continuous history, state 'finished', and
    the pre-crash epoch metrics preserved verbatim. The reference loses
    the job when its TrainJob pod dies (tolerance exists only within a
    merge, util.go:144-166)."""
    dep, client, tmp_path = standalone_stack
    paths = write_blob_files(tmp_path, n_train=4000)
    client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])

    epochs = 30
    req = TrainRequest(model_type="mlp", batch_size=16, epochs=epochs,
                       dataset="blobs", lr=0.05,
                       options=TrainOptions(default_parallelism=2, k=1,
                                            static_parallelism=True,
                                            max_restarts=1,
                                            # no goal-accuracy early
                                            # stop: a fast-converging
                                            # run must not end before
                                            # the kill lands
                                            goal_accuracy=200.0))
    job_id = client.v1().networks().train(req)

    rec = _kill_in_window(dep, tmp_home, job_id, epochs)  # the crash
    pre_crash = _read_manifest(tmp_home, job_id)
    assert pre_crash.get("history"), "mid-job manifest must carry history"

    # the SAME record must be respawned (not failed): restarts consumed,
    # new child process, job still registered
    deadline = time.time() + 120
    while time.time() < deadline:
        with dep.ps._jobs_lock:
            alive = dep.ps.jobs.get(job_id)
        if alive is not None and alive.restarts == 1:
            break
        if alive is None:
            break  # may have already finished post-restart — checked below
        time.sleep(0.1)

    history = wait_history(client, job_id, timeout=300)
    assert rec.restarts == 1, "watchdog did not restart the crashed job"
    # one CONTINUOUS history across the crash: full epoch count, and the
    # pre-crash epochs' metrics preserved verbatim from the manifest
    assert len(history.data.train_loss) == epochs
    saved = pre_crash["history"]["train_loss"]
    assert history.data.train_loss[: len(saved)] == saved
    assert dep.ps.wait_for_job(job_id, timeout=60)

    # the finished model is inferable like any other
    x = np.load(paths["xte"])[:3]
    preds = client.v1().networks().infer(job_id, x.tolist())
    assert len(preds) == 3


def test_two_crashes_two_restarts_continuous_history(standalone_stack,
                                                     tmp_home):
    """max_restarts=2 survives TWO crashes: the second restart resumes
    from the checkpoint the FIRST restarted incarnation wrote (chained
    resume-from-self), and the final history is one continuous run."""
    dep, client, tmp_path = standalone_stack
    paths = write_blob_files(tmp_path, n_train=20000)
    client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])
    epochs = 40
    req = TrainRequest(model_type="mlp", batch_size=16, epochs=epochs,
                       dataset="blobs", lr=0.05,
                       options=TrainOptions(default_parallelism=2, k=1,
                                            static_parallelism=True,
                                            max_restarts=2,
                                            goal_accuracy=200.0))
    job_id = client.v1().networks().train(req)

    _kill_in_window(dep, tmp_home, job_id, epochs, expect_restarts=0)
    first_crash_epoch = _read_manifest(tmp_home, job_id).get("epoch", 0)
    assert first_crash_epoch >= 1
    # require the RESTARTED incarnation to have checkpointed past the
    # first crash's manifest before the second kill, so the third
    # incarnation genuinely resumes from incarnation #2's checkpoint
    # (chained recovery), not a single-hop resume of the first one
    rec = _kill_in_window(dep, tmp_home, job_id, epochs,
                          expect_restarts=1,
                          min_epoch=first_crash_epoch + 1)
    second_crash = _read_manifest(tmp_home, job_id)
    assert second_crash.get("epoch", 0) > first_crash_epoch

    history = wait_history(client, job_id, timeout=420)
    assert rec.restarts == 2
    assert len(history.data.train_loss) == epochs
    # the third incarnation's restored prefix equals what was durable
    # at the second crash — history chained across BOTH restarts
    saved = second_crash["history"]["train_loss"]
    assert history.data.train_loss[: len(saved)] == saved
    assert dep.ps.wait_for_job(job_id, timeout=120)


def test_restart_budget_exhausted_fails_job(standalone_stack, tmp_home):
    """A second crash beyond max_restarts=1 must FAIL the job (no
    infinite respawn loop): the watchdog consumes its one restart on
    the first kill, and the second kill deregisters the job with the
    unexpected-exit error."""
    dep, client, tmp_path = standalone_stack
    paths = write_blob_files(tmp_path, n_train=20000)
    client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])
    epochs = 40
    req = TrainRequest(model_type="mlp", batch_size=16, epochs=epochs,
                       dataset="blobs", lr=0.05,
                       options=TrainOptions(default_parallelism=2, k=1,
                                            static_parallelism=True,
                                            max_restarts=1,
                                            # no goal-accuracy early
                                            # stop: a fast-converging
                                            # run must not end before
                                            # the kill lands
                                            goal_accuracy=200.0))
    job_id = client.v1().networks().train(req)

    # first kill: consumed by the one restart; second: budget exhausted
    _kill_in_window(dep, tmp_home, job_id, epochs, expect_restarts=0)
    rec = _kill_in_window(dep, tmp_home, job_id, epochs,
                          expect_restarts=1)

    # the job must deregister as FAILED — no third incarnation
    assert dep.ps.wait_for_job(job_id, timeout=120)
    assert rec.restarts == 1
    # and it never wrote a completed history (the run was cut short)
    from kubeml_tpu.api.errors import KubeMLException
    try:
        h = client.v1().histories().get(job_id)
        assert len(h.data.train_loss) < epochs
    except KubeMLException:
        pass  # no history at all is the expected common case


def test_sigterm_preemption_reschedules_without_budget(standalone_stack,
                                                       tmp_home):
    """Preemption grace end-to-end: SIGTERM the standalone child mid-job
    (the platform's eviction notice). The jobserver's handler drains the
    in-flight round, writes a round-granular checkpoint, posts
    /preempted to the PS and exits; the watchdog reschedules WITHOUT
    consuming the crash-restart budget — proven by max_restarts=0, where
    a crash-path exit would fail the job instead. The rescheduled
    incarnation resumes at the round cursor and finishes with one
    continuous history carrying preemptions=1."""
    import signal

    dep, client, tmp_path = standalone_stack
    paths = write_blob_files(tmp_path, n_train=4000)
    client.v1().datasets().create(
        "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])

    epochs = 30
    req = TrainRequest(model_type="mlp", batch_size=16, epochs=epochs,
                       dataset="blobs", lr=0.05,
                       options=TrainOptions(default_parallelism=2, k=1,
                                            static_parallelism=True,
                                            max_restarts=0,
                                            checkpoint_every_rounds=8,
                                            goal_accuracy=200.0))
    job_id = client.v1().networks().train(req)

    rec = _kill_in_window(dep, tmp_home, job_id, epochs,
                          sig=signal.SIGTERM)

    # the record must be rescheduled, not failed: preemption counted,
    # restart budget untouched
    deadline = time.time() + 120
    while time.time() < deadline:
        with dep.ps._jobs_lock:
            alive = dep.ps.jobs.get(job_id)
        if alive is None or rec.preemptions >= 1:
            break
        time.sleep(0.1)
    assert rec.preemptions == 1, "PS never saw the /preempted grace post"
    assert rec.restarts == 0, "preemption must not consume max_restarts"

    history = wait_history(client, job_id, timeout=300)
    assert len(history.data.train_loss) == epochs
    assert history.data.preemptions == 1
    assert history.data.restarts == 0
    assert dep.ps.wait_for_job(job_id, timeout=60)
