"""Experiment harness tests: grid expansion, TTA math, live sweep."""

import json
import sys
import os

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from experiments.common.experiment import (KubemlExperiment, expand_grid,
                                           time_to_accuracy)
from experiments.common.metrics import SystemMetricsSampler
from kubeml_tpu.api.types import (History, JobHistory, TrainOptions,
                                  TrainRequest)


def _hist(accs, durs):
    return History(
        id="x",
        task=TrainRequest(model_type="m", batch_size=1, epochs=len(accs),
                          dataset="d", lr=0.1, options=TrainOptions()),
        data=JobHistory(accuracy=list(accs), epoch_duration=list(durs),
                        train_loss=[0.0] * len(accs),
                        validation_loss=[0.0] * len(accs),
                        parallelism=[1] * len(accs)))


def test_expand_grid_cartesian():
    grid = {"batch": [1, 2], "k": [-1], "parallelism": [4, 8]}
    cfgs = expand_grid(grid)
    assert len(cfgs) == 4
    assert {"batch": 2, "k": -1, "parallelism": 8} in cfgs


def test_time_to_accuracy():
    h = _hist([50.0, 80.0, 95.0], [10.0, 10.0, 10.0])
    assert time_to_accuracy(h, 70.0) == 20.0
    assert time_to_accuracy(h, 95.0) == 30.0
    assert time_to_accuracy(h, 99.0) is None


def test_metrics_sampler_collects():
    s = SystemMetricsSampler(interval=0.05)
    with s:
        import time
        time.sleep(0.3)
    assert len(s.samples) >= 2
    assert {"ts", "cpu_pct", "mem_pct", "proc_rss_mb"} <= set(s.samples[0])


@pytest.fixture()
def live(tmp_path, tmp_home, mesh8, monkeypatch):
    from kubeml_tpu.control.client import KubemlClient
    from kubeml_tpu.control.deployment import start_deployment
    dep = start_deployment(mesh=mesh8)
    rng = np.random.RandomState(0)
    y = rng.randint(0, 3, 600).astype(np.int32)
    x = rng.randn(600, 8).astype(np.float32) * 1.5
    x[np.arange(600), y * 2] += 3.0
    paths = {}
    for name, arr in (("xtr", x), ("ytr", y), ("xte", x[:100]),
                      ("yte", y[:100])):
        p = tmp_path / f"{name}.npy"
        np.save(p, arr)
        paths[name] = str(p)
    client = KubemlClient(dep.controller_url)
    client.v1().datasets().create("blobs", paths["xtr"], paths["ytr"],
                                  paths["xte"], paths["yte"])
    yield client
    dep.stop()


def test_grid_sweep_live(live):
    exp = KubemlExperiment(live, timeout=300)
    results = exp.run_grid("mlp", "blobs",
                           {"batch": [32], "k": [2], "parallelism": [2, 4]},
                           epochs=2, lr=0.1)
    assert len(results) == 2
    rows = exp.rows([50.0])
    for row in rows:
        assert row["epochs_run"] == 2
        assert row["train_time_s"] > 0
        assert row["max_accuracy"] is not None
    # the blob task is separable: a 50%-accuracy TTA should be hit
    assert any(r["tta50_s"] is not None for r in rows)
    df = exp.to_frame([50.0])
    assert {"batch", "parallelism", "tta50_s"} <= set(df.columns)


@pytest.mark.parametrize("grid", ["lstm", "bert"])
def test_baseline_text_grids_run(grid, tmp_home, tmp_path):
    """BASELINE.json configs 4-5 run end-to-end on synthetic stand-ins."""
    from experiments.train import main as sweep_main
    out = tmp_path / f"{grid}.jsonl"
    rc = sweep_main(["--grid", grid, "--local", "--synthetic",
                     "--limit", "1", "--epochs", "1",
                     "--out", str(out)])
    assert rc == 0
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert len(rows) == 1 and rows[0]["epochs_run"] == 1


def test_resnet50_grid_is_autoscale():
    """BASELINE.json config 3 uses dynamic parallelism (autoscale)."""
    from experiments.train import GRIDS
    assert GRIDS["resnet50"]["static"] is False
    assert GRIDS["resnet50"]["function"] == "resnet50"


def test_single_node_baseline_arm(tmp_path):
    """The reference's TF/Keras comparison arm, as a plain JAX loop."""
    from experiments.baseline_train import main as baseline_main
    out = tmp_path / "baseline.jsonl"
    rc = baseline_main(["--function", "mlp", "--epochs", "2",
                        "--batch", "32", "--lr", "0.1",
                        "--samples", "256", "--out", str(out)])
    assert rc == 0
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert len(rows) == 2
    assert rows[0]["arm"] == "single-node-baseline"
    assert rows[1]["train_loss"] <= rows[0]["train_loss"] * 1.2


def test_real_digits_arm():
    """The real-data arm: genuine sklearn digits on the MNIST canvas,
    stratified 80/20, values in [0,1] with the true pixels centered."""
    from experiments.data import real_digits

    xtr, ytr, xte, yte = real_digits()
    assert xtr.shape[1:] == (28, 28, 1) and xte.shape[1:] == (28, 28, 1)
    assert len(xtr) + len(xte) == 1797
    assert abs(len(xte) / 1797 - 0.2) < 0.01
    assert xtr.min() >= 0.0 and xtr.max() <= 1.0
    # stratified: every class present in both splits
    import numpy as np
    assert set(np.unique(ytr)) == set(range(10)) == set(np.unique(yte))
    # the 8x8 payload sits centered; the border is the zero canvas
    assert np.abs(xtr[:, :9, :, 0]).sum() == 0.0
    assert xtr[:, 10:18, 10:18, 0].sum() > 0


def test_lenet_digits_grid_registered():
    from experiments.common import utils as grids
    from experiments.train import GRIDS

    spec = GRIDS["lenet-digits"]
    assert spec["dataset"] == "digits" and spec["shuffle"] is True
    assert spec["grid"] is grids.LENET_DIGITS_GRID
    assert spec["tta"] == 95.0


def test_bench_text_engine_arm_runs():
    """The committed text benchmark harness (experiments/bench_text.py,
    the lstm/bert BASELINE rows in results/) keeps running — tiny
    shapes, API/shape bitrot guard, not a measurement."""
    from experiments.bench_text import bench_engine_text

    # workers must be a multiple of the mesh data-axis size (8 virtual
    # devices under the test conftest)
    row = bench_engine_text("lstm", k=2, batch=8, seq_len=16, vocab=500,
                            workers=8, epoch_samples=64, timed_epochs=1)
    assert row["bench"] == "lstm_engine_throughput"
    assert row["samples_per_sec_per_chip"] > 0
    # both fields are independently rounded to 1 decimal; compare loosely
    assert row["tokens_per_sec_per_chip"] == pytest.approx(
        row["samples_per_sec_per_chip"] * 16, rel=0.05)


def test_bench_text_generate_arm_runs():
    """Decode-throughput arm bitrot guard (tiny shapes)."""
    from experiments.bench_text import bench_generate

    row = bench_generate(T_prompt=8, n_new=8, batch=2, iters=1)
    assert row["bench"] == "gpt_kvcache_decode"
    assert row["decode_tokens_per_sec"] > 0
    assert row["ms_per_generated_token"] > 0
