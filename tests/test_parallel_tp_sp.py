"""Tensor + sequence parallelism: ring attention exactness, TP shardings.

Runs on the 8-virtual-CPU-device mesh (conftest).
"""

import jax
from kubeml_tpu import compat
import jax.numpy as jnp
import numpy as np
import pytest

from kubeml_tpu.models import get_builtin
from kubeml_tpu.ops.attention import multi_head_attention, padding_bias
from kubeml_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS, SEQ_AXIS,
                                      make_mesh)
from kubeml_tpu.parallel.ring_attention import ring_self_attention
from kubeml_tpu.parallel.tp import (BERT_TP_RULES, shard_variables,
                                    spec_for, tree_specs)

B, T, H, D = 2, 32, 4, 8


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh(n_data=1, n_model=1, n_seq=8)


def _qkv(rng):
    q = rng.randn(B, T, H, D).astype(np.float32)
    k = rng.randn(B, T, H, D).astype(np.float32)
    v = rng.randn(B, T, H, D).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


def test_ring_attention_matches_full(seq_mesh):
    rng = np.random.RandomState(0)
    q, k, v = _qkv(rng)
    pad = np.ones((B, T), np.float32)
    pad[0, 20:] = 0.0  # ragged padding crossing block boundaries
    pad[1, 5:9] = 0.0  # interior masked tokens
    ref = multi_head_attention(q, k, v, padding_bias(jnp.asarray(pad)))
    out = ring_self_attention(q, k, v, jnp.asarray(pad), seq_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_causal(seq_mesh):
    rng = np.random.RandomState(1)
    q, k, v = _qkv(rng)
    pad = jnp.ones((B, T))
    causal_bias = jnp.where(
        jnp.arange(T)[:, None] >= jnp.arange(T)[None, :], 0.0,
        -1e9)[None, None]
    ref = multi_head_attention(q, k, v, causal_bias)
    out = ring_self_attention(q, k, v, pad, seq_mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_causal_with_padding(seq_mesh):
    """Causal AND padding together: doubly-masked positions (pad inside
    the causal window, stacked -2e9 bias) stay exact and finite."""
    rng = np.random.RandomState(3)
    q, k, v = _qkv(rng)
    pad = np.ones((B, T), np.float32)
    pad[0, 10:] = 0.0
    pad[1, 3:7] = 0.0
    causal_part = jnp.where(
        jnp.arange(T)[:, None] >= jnp.arange(T)[None, :], 0.0,
        -1e9)[None, None]
    bias = causal_part + padding_bias(jnp.asarray(pad))
    ref = multi_head_attention(q, k, v, bias)
    out = ring_self_attention(q, k, v, jnp.asarray(pad), seq_mesh,
                              causal=True)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_match(seq_mesh):
    """The ring is differentiable and its grads equal full attention's."""
    rng = np.random.RandomState(2)
    q, k, v = _qkv(rng)
    pad = jnp.ones((B, T))

    def loss_ref(q, k, v):
        return (multi_head_attention(q, k, v,
                                     padding_bias(pad)) ** 2).sum()

    def loss_ring(q, k, v):
        return (ring_self_attention(q, k, v, pad, seq_mesh) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-4)


# ------------------------------------------------------------ ulysses


@pytest.fixture(scope="module")
def seq4_mesh():
    # 4-way seq axis so heads (4) divide it — ulysses' requirement
    return make_mesh(n_data=1, n_model=1, n_seq=4)


def test_ulysses_matches_full(seq4_mesh):
    from kubeml_tpu.parallel.ulysses import ulysses_self_attention
    rng = np.random.RandomState(0)
    q, k, v = _qkv(rng)
    pad = np.ones((B, T), np.float32)
    pad[0, 20:] = 0.0  # ragged padding crossing block boundaries
    pad[1, 5:9] = 0.0  # interior masked tokens
    ref = multi_head_attention(q, k, v, padding_bias(jnp.asarray(pad)))
    out = ulysses_self_attention(q, k, v, jnp.asarray(pad), seq4_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_causal_with_padding(seq4_mesh):
    from kubeml_tpu.ops.attention import composed_bias
    from kubeml_tpu.parallel.ulysses import ulysses_self_attention
    rng = np.random.RandomState(3)
    q, k, v = _qkv(rng)
    pad = np.ones((B, T), np.float32)
    pad[0, 10:] = 0.0
    pad[1, 3:7] = 0.0
    ref = multi_head_attention(q, k, v,
                               composed_bias(jnp.asarray(pad), True, T))
    out = ulysses_self_attention(q, k, v, jnp.asarray(pad), seq4_mesh,
                                 causal=True)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_grads_match(seq4_mesh):
    """Both all-to-alls are differentiable; grads equal full attention's."""
    from kubeml_tpu.parallel.ulysses import ulysses_self_attention
    rng = np.random.RandomState(2)
    q, k, v = _qkv(rng)
    pad = jnp.ones((B, T))

    def loss_ref(q, k, v):
        return (multi_head_attention(q, k, v,
                                     padding_bias(pad)) ** 2).sum()

    def loss_uly(q, k, v):
        return (ulysses_self_attention(q, k, v, pad, seq4_mesh) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_uly):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-4)


def test_ulysses_indivisible_heads_raises(seq_mesh):
    """H=4 on an 8-way seq axis cannot head-shard: loud error, not a
    wrong answer."""
    from kubeml_tpu.parallel.ulysses import ulysses_self_attention
    rng = np.random.RandomState(0)
    q, k, v = _qkv(rng)
    with pytest.raises(ValueError, match="head count"):
        ulysses_self_attention(q, k, v, jnp.ones((B, T)), seq_mesh)


# ----------------------------------------------------------------- TP


def test_spec_rules():
    assert spec_for("layer_0/q/kernel", BERT_TP_RULES) == \
        jax.sharding.PartitionSpec(None, MODEL_AXIS, None)
    assert spec_for("layer_1/out/kernel", BERT_TP_RULES) == \
        jax.sharding.PartitionSpec(MODEL_AXIS, None, None)
    assert spec_for("tok_embed/embedding", BERT_TP_RULES) == \
        jax.sharding.PartitionSpec()


def test_bert_tp_forward_matches_replicated():
    """BERT forward with Megatron-sharded params == replicated forward."""
    mesh = make_mesh(n_data=2, n_model=2, n_seq=2)
    model = get_builtin("bert-tiny")()
    rng = np.random.RandomState(0)
    x = rng.randint(1, 1000, size=(4, 16)).astype(np.int32)
    x[:, 12:] = 0
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x)})
    ref = model.module.apply(variables, jnp.asarray(x), train=False)

    sharded_vars = shard_variables(variables, mesh, BERT_TP_RULES)
    # at least one param actually got a non-trivial sharding
    shardings = [v.sharding.spec for v in
                 jax.tree_util.tree_leaves(sharded_vars)
                 if hasattr(v, "sharding")]
    assert any(s != jax.sharding.PartitionSpec() for s in shardings)

    # jit infers the partitioning from the input NamedShardings; XLA's
    # SPMD partitioner inserts the TP collectives
    out = jax.jit(lambda v, x: model.module.apply(v, x, train=False))(
        sharded_vars, jnp.asarray(x))
    # bf16 compute: sharded matmuls change reduction order; one-ulp scale
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def test_tp_fallback_replicates_indivisible():
    """A dim not divisible by the axis falls back to replication instead
    of crashing (2 heads on a 4-way model axis)."""
    mesh = make_mesh(n_data=2, n_model=4, n_seq=1)
    tree = {"layer_0": {"q": {"kernel": jnp.zeros((8, 2, 4))}}}
    out = shard_variables(tree, mesh, BERT_TP_RULES)
    spec = out["layer_0"]["q"]["kernel"].sharding.spec
    assert spec == jax.sharding.PartitionSpec()


def test_kavg_trains_tp_sharded_variables():
    """DP x TP training: the K-avg round on a 4x2 mesh with Megatron-
    sharded BERT variables must produce the same averaged weights as the
    fully-replicated run on a pure-DP mesh (same worker count, same
    data) — GSPMD handles the model axis inside each DP lane while the
    merge psums over `data` only."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.parallel.kavg import KAvgEngine
    from kubeml_tpu.parallel.mesh import make_mesh
    from kubeml_tpu.parallel.tp import BERT_TP_RULES, shard_variables

    model = get_builtin("bert-tiny")()
    rng = np.random.RandomState(0)
    W, S, B, T = 4, 2, 4, 16
    x = rng.randint(1, 1000, size=(W, S, B, T)).astype(np.int32)
    y = rng.randint(0, 2, size=(W, S, B)).astype(np.int32)
    batch = {"x": jnp.asarray(x), "y": jnp.asarray(y)}
    masks = dict(sample_mask=np.ones((W, S, B), np.float32),
                 step_mask=np.ones((W, S), np.float32),
                 worker_mask=np.ones(W, np.float32))
    rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x[0, 0])})

    def run(mesh, variables):
        # plain SGD: adamw's g/(sqrt(v)+eps) amplifies bf16 layout noise
        # on near-zero grads, which would make exact comparison
        # ill-conditioned without changing what this test proves
        import optax
        eng = KAvgEngine(mesh, model.loss, model.metrics,
                         lambda lr, epoch: optax.sgd(lr), donate=False)
        out, stats = eng.train_round(variables, batch, rngs=rngs,
                                     lr=1e-2, epoch=0, **masks)
        assert stats.contributors == W
        return out

    ref = run(make_mesh(n_data=4), variables)

    mesh_tp = make_mesh(n_data=4, n_model=2)
    sharded = shard_variables(variables, mesh_tp, BERT_TP_RULES)
    out_tp = run(mesh_tp, sharded)

    for pr, pt in zip(jax.tree_util.tree_leaves(ref),
                      jax.tree_util.tree_leaves(out_tp)):
        np.testing.assert_allclose(np.asarray(pr), np.asarray(pt),
                                   rtol=2e-2, atol=2e-3)


def test_gpt_tp_forward_matches_replicated():
    """The decoder blocks share the BERT blocks' param layout, so the
    same Megatron rule table (GPT_TP_RULES) TP-shards the causal model."""
    from kubeml_tpu.parallel.tp import GPT_TP_RULES

    mesh = make_mesh(n_data=2, n_model=2, n_seq=2)
    from tests.test_models_gpt import TinyGPT
    model = TinyGPT()
    rng = np.random.RandomState(0)
    x = rng.randint(1, 64, size=(4, 16)).astype(np.int32)
    x[:, 12:] = 0
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x)})
    ref = model.module.apply(variables, jnp.asarray(x), train=False)

    sharded_vars = shard_variables(variables, mesh, GPT_TP_RULES)
    shardings = [v.sharding.spec for v in
                 jax.tree_util.tree_leaves(sharded_vars)
                 if hasattr(v, "sharding")]
    assert any(s != jax.sharding.PartitionSpec() for s in shardings)

    out = jax.jit(lambda v, x: model.module.apply(v, x, train=False))(
        sharded_vars, jnp.asarray(x))
    # per-token vocab logits: same bf16 tolerance as the SP parity tests
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=5e-2, atol=6e-2)


def test_kavg_trains_tp_sharded_gpt():
    """DP x TP K-avg training of the causal LM: loss falls with
    Megatron-sharded variables on a 4x2 mesh."""
    from kubeml_tpu.parallel.kavg import KAvgEngine
    from kubeml_tpu.parallel.tp import GPT_TP_RULES, shard_variables
    from tests.test_models_gpt import TinyGPT, make_lm_task

    mesh = make_mesh(n_data=4, n_model=2)
    model = TinyGPT()
    rng = np.random.RandomState(0)
    W, S, B, T = 4, 2, 8, 16
    x = make_lm_task(rng, W * S * B).reshape(W, S, B, T)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x[0, 0])})
    variables = shard_variables(variables, mesh, GPT_TP_RULES)
    engine = KAvgEngine(mesh, model.loss, model.metrics,
                        model.configure_optimizers, donate=False)
    batch = {"x": jnp.asarray(x)}
    masks = dict(sample_mask=np.ones((W, S, B)), step_mask=np.ones((W, S)),
                 worker_mask=np.ones(W))
    first = last = None
    for _ in range(6):
        rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)
        variables, stats = engine.train_round(
            variables, batch, rngs=rngs, lr=3e-3, epoch=0, **masks)
        last = stats.loss_sum.sum() / stats.step_count.sum()
        if first is None:
            first = last
    assert last < first, (first, last)


# ----------------------------------------------- seq-parallel TRAINING


def _sp_train_compare(make_model, make_batch, impl):
    """One K-avg round + eval on (data=2, seq=2) vs pure-DP (data=2):
    averaged weights, round loss, and eval metrics must match the dense
    run to bf16 reduction-order noise. Exercises loss AND grads through
    the ring/all-to-all attention inside the engine path (check_vma=True
    round — see KAvgEngine.batch_seq_dims)."""
    import optax

    from kubeml_tpu.parallel.kavg import KAvgEngine

    rng = np.random.RandomState(0)
    W, S, B, T = 2, 2, 4, 32
    batch = make_batch(rng, W, S, B, T)
    masks = dict(sample_mask=np.ones((W, S, B), np.float32),
                 step_mask=np.ones((W, S), np.float32),
                 worker_mask=np.ones(W, np.float32))
    rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)

    model0 = make_model()
    variables = model0.init_variables(
        jax.random.PRNGKey(0),
        jax.tree_util.tree_map(lambda a: jnp.asarray(a[0, 0]), batch))

    def run(mesh, model):
        eng = KAvgEngine(mesh, model.loss, model.metrics,
                         lambda lr, e: optax.sgd(lr), donate=False,
                         batch_seq_dims=model.seq_batch_dims)
        jb = jax.tree_util.tree_map(jnp.asarray, batch)
        out, stats = eng.train_round(variables, jb, rngs=rngs, lr=1e-2,
                                     epoch=0, **masks)
        ev = eng.eval_round(out, jb, masks["sample_mask"])
        return out, float(np.asarray(stats.loss_sum).sum()), ev

    # dropout 0 for determinism: local seq blocks draw different dropout
    # masks than the dense layout, which is fine in production but would
    # blur this equality test
    ref_model = make_model()
    ref_model._module = ref_model.module.clone(dropout=0.0)
    ref, loss_ref, ev_ref = run(
        make_mesh(n_data=2, devices=jax.devices()[:2]), ref_model)

    sp_model = make_model()
    sp_model._module = sp_model.module.clone(dropout=0.0)
    sp_model.enable_seq_parallel(impl)
    sp, loss_sp, ev_sp = run(
        make_mesh(n_data=2, n_seq=2, devices=jax.devices()[:4]), sp_model)

    for a, b in zip(jax.tree_util.tree_leaves(ref),
                    jax.tree_util.tree_leaves(sp)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-2, atol=2e-3)
    assert abs(loss_ref - loss_sp) < 5e-3 * max(1.0, abs(loss_ref))
    assert abs(ev_ref["loss"] - ev_sp["loss"]) < 5e-3
    assert ev_ref["n"] == ev_sp["n"]


def _bert_sp_batch(rng, W, S, B, T):
    return {"x": rng.randint(1, 1000, size=(W, S, B, T)).astype(np.int32),
            "y": rng.randint(0, 2, size=(W, S, B)).astype(np.int32)}


def _lm_sp_batch(rng, W, S, B, T):
    start = rng.randint(1, 63, size=(W * S * B, 1))
    seq = (start + np.arange(T)[None, :] - 1) % 63 + 1
    return {"x": seq.reshape(W, S, B, T).astype(np.int32)}


def test_kavg_trains_seq_parallel_bert_ring():
    _sp_train_compare(lambda: get_builtin("bert-tiny")(), _bert_sp_batch,
                      "ring")


def test_kavg_trains_seq_parallel_gpt_ring():
    from tests.test_models_gpt import TinyGPT
    _sp_train_compare(TinyGPT, _lm_sp_batch, "ring")


def test_kavg_trains_seq_parallel_gpt_ulysses():
    from tests.test_models_gpt import TinyGPT
    _sp_train_compare(TinyGPT, _lm_sp_batch, "ulysses")


def test_sp_loss_handles_padding_across_shards():
    """Right-padded rows: the SP LM loss (ppermute boundary target +
    global-last masking) must equal the dense loss exactly."""
    from jax.sharding import PartitionSpec as P

    from kubeml_tpu.models.gpt import (_lm_per_example, _lm_per_example_sp)
    from tests.test_models_gpt import TinyGPT

    model = TinyGPT()
    # f32 modules so dense-vs-ring attention noise cannot blur the
    # boundary/masking logic this test pins down
    model._module = model.module.clone(dtype=jnp.float32)
    rng = np.random.RandomState(1)
    B, T = 4, 32
    x = rng.randint(1, 63, size=(B, T)).astype(np.int32)
    x[0, 20:] = 0   # right padding ending inside shard 2 (of 4)
    x[1, 8:] = 0    # ends inside shard 1
    x[2, :] = 0     # fully padded row
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x)})
    dense_logits = model.module.apply(variables, jnp.asarray(x),
                                      train=False)
    ref = np.asarray(_lm_per_example(dense_logits, jnp.asarray(x)))

    mesh = make_mesh(n_data=1, n_seq=4)
    sp_module = model.module.clone(seq_axis=SEQ_AXIS)

    def body(v, x_local):
        logits = sp_module.apply(v, x_local, train=False)
        return _lm_per_example_sp(logits, x_local, SEQ_AXIS)

    out = jax.jit(compat.shard_map(
        body, mesh=mesh, in_specs=(P(), P(None, SEQ_AXIS)),
        out_specs=P(), check_vma=False))(variables, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)
