"""On-device round assembly: HBM-resident dataset cache + index-fed
rounds (data/device_cache.py).

The load-bearing property is BIT-EXACTNESS: an index-fed round must
produce byte-identical averaged weights and loss sums to the host-staged
round it replaces — the gathered values are the same samples, the rng
stream is the same draw, and every padded-slot divergence (cycle-pad
gathers vs zero padding) is nullified by the masks. These tests enforce
it for both engines, both cache layouts, and the shuffled permutation,
plus the job-level selection/fallback logic.
"""

import time

import jax
import numpy as np
import pytest

from kubeml_tpu.api.errors import JobNotFoundError, KubeMLException
from kubeml_tpu.api.types import TrainOptions, TrainRequest, TrainTask
from kubeml_tpu.data.device_cache import DeviceDatasetCache
from kubeml_tpu.data.loader import RoundLoader
from kubeml_tpu.data.registry import DatasetRegistry
from kubeml_tpu.models import get_builtin
from kubeml_tpu.models.base import KubeDataset
from kubeml_tpu.parallel.kavg import KAvgEngine
from kubeml_tpu.train.job import TrainJob


class ToyDataset(KubeDataset):
    dataset = "blobs"


class ScaledDataset(KubeDataset):
    """Non-identity host transform WITHOUT a device twin: structurally
    ineligible for the cache (the raw cached arrays would gather
    different values than staging ships)."""

    dataset = "blobs"

    def transform_train(self, data, labels):
        return {"x": data * 0.5, "y": labels}


def make_blobs(reg, n_train=800, n_test=200, dim=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)

    def split(n):
        y = rng.randint(0, classes, n).astype(np.int32)
        x = rng.randn(n, dim).astype(np.float32) * 2.0
        x[np.arange(n), y % dim] += 3.0
        return x, y

    xtr, ytr = split(n_train)
    xte, yte = split(n_test)
    return reg.create("blobs", xtr, ytr, xte, yte)


@pytest.fixture()
def setup(tmp_path, tmp_home, mesh8):
    reg = DatasetRegistry()
    handle = make_blobs(reg)
    model = get_builtin("mlp")(hidden=16, num_classes=4)
    return reg, handle, model, mesh8


def _init_variables(model, handle, batch=32):
    x, y = handle.doc_range("train", 0, 1)
    sample = {"x": np.asarray(x[:batch]), "y": np.asarray(y[:batch])}
    return model.init_variables(jax.random.PRNGKey(0), sample)


def _tree_equal(a, b) -> bool:
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


@pytest.mark.parametrize("shuffle", [False, True],
                         ids=["sharded", "shuffled-replicated"])
def test_kavg_index_rounds_bit_exact(setup, shuffle):
    """Index-fed K-avg rounds == host-staged rounds, bit for bit, for
    a full epoch (ragged tail rounds, inactive padded workers and all).
    shuffle=True forces the replicated layout with global indices."""
    reg, handle, model, mesh = setup
    ds = ToyDataset()
    loader_h = RoundLoader(handle, ds, n_lanes=8, seed=3, shuffle=shuffle)
    loader_i = RoundLoader(handle, ds, n_lanes=8, seed=3, shuffle=shuffle)
    plan = loader_h.plan(5, k=2, batch_size=32)
    W, S, B = loader_i.round_geometry(plan)

    layout = "replicated" if shuffle else "sharded"
    cache = DeviceDatasetCache(handle, mesh, layout=layout)
    cache.ensure(plan, W)

    eng_h = KAvgEngine(mesh, model.loss, model.metrics,
                       model.configure_optimizers, donate=False)
    eng_i = KAvgEngine(mesh, model.loss, model.metrics,
                       model.configure_optimizers, donate=False)
    vars_h = _init_variables(model, handle)
    vars_i = jax.tree_util.tree_map(np.asarray, vars_h)

    n_rounds = 0
    for rb_h, rb_i in zip(loader_h.epoch_rounds(plan, epoch=0),
                          loader_i.epoch_index_rounds(
                              plan, epoch=0,
                              lane_starts=cache.lane_starts)):
        # the two sources must agree on everything but the batch payload
        assert np.array_equal(rb_h.sample_mask, rb_i.sample_mask)
        assert np.array_equal(rb_h.step_mask, rb_i.step_mask)
        assert np.array_equal(rb_h.worker_mask, rb_i.worker_mask)
        assert np.array_equal(rb_h.rngs, rb_i.rngs)
        assert rb_i.batch["idx"].dtype == np.int32
        vars_h, st_h = eng_h.train_round(
            vars_h, rb_h.batch, rb_h.sample_mask, rb_h.step_mask,
            rb_h.worker_mask, rb_h.rngs, lr=0.1, epoch=0)
        vars_i, st_i = eng_i.train_round_indexed(
            vars_i, cache, rb_i.batch["idx"], rb_i.sample_mask,
            rb_i.step_mask, rb_i.worker_mask, rb_i.rngs, lr=0.1, epoch=0)
        assert np.array_equal(st_h.loss_sum, st_i.loss_sum)
        n_rounds += 1
    assert n_rounds >= 2  # the epoch actually exercised multiple rounds
    assert _tree_equal(vars_h, vars_i)


def test_kavg_grouped_index_rounds_bit_exact(setup):
    """train_rounds_indexed ([R, W, S, B] indices, one dispatch) ==
    R host-staged single-round dispatches."""
    reg, handle, model, mesh = setup
    ds = ToyDataset()
    loader_h = RoundLoader(handle, ds, n_lanes=8, seed=5)
    loader_i = RoundLoader(handle, ds, n_lanes=8, seed=5)
    plan = loader_h.plan(8, k=2, batch_size=16)
    W, S, B = loader_i.round_geometry(plan)
    cache = DeviceDatasetCache(handle, mesh, layout="sharded")
    cache.ensure(plan, W)

    eng_h = KAvgEngine(mesh, model.loss, model.metrics,
                       model.configure_optimizers, donate=False)
    eng_i = KAvgEngine(mesh, model.loss, model.metrics,
                       model.configure_optimizers, donate=False)
    vars_h = _init_variables(model, handle, batch=16)
    vars_i = jax.tree_util.tree_map(np.asarray, vars_h)

    host = list(loader_h.epoch_rounds(plan, epoch=0))
    idxed = list(loader_i.epoch_index_rounds(plan, epoch=0,
                                             lane_starts=cache.lane_starts))
    R = 2
    assert len(host) >= R
    for rb in host[:R]:
        vars_h, _ = eng_h.train_round(
            vars_h, rb.batch, rb.sample_mask, rb.step_mask,
            rb.worker_mask, rb.rngs, lr=0.1, epoch=0)
    group = idxed[:R]
    vars_i, stats = eng_i.train_rounds_indexed(
        vars_i, cache,
        np.stack([rb.batch["idx"] for rb in group]),
        np.stack([rb.sample_mask for rb in group]),
        np.stack([rb.step_mask for rb in group]),
        np.stack([rb.worker_mask for rb in group]),
        np.stack([rb.rngs for rb in group]), lr=0.1, epoch=0)
    assert stats.loss_sum.shape[0] == R
    assert _tree_equal(vars_h, vars_i)


def test_syncdp_index_steps_bit_exact(setup):
    """Index-fed sync-DP steps == host-staged steps (replicated cache,
    global indices riding the same [W,S,B]->[S,W*B] reflow)."""
    from kubeml_tpu.parallel.syncdp import SyncDPEngine

    reg, handle, model, mesh = setup
    ds = ToyDataset()
    loader_h = RoundLoader(handle, ds, n_lanes=8, seed=7)
    loader_i = RoundLoader(handle, ds, n_lanes=8, seed=7)
    plan = loader_h.plan(4, k=2, batch_size=32)
    loader_i.round_geometry(plan)
    cache = DeviceDatasetCache(handle, mesh, layout="replicated")
    cache.ensure()

    eng_h = SyncDPEngine(mesh, model.loss, model.configure_optimizers,
                         donate=False)
    eng_i = SyncDPEngine(mesh, model.loss, model.configure_optimizers,
                         donate=False)
    variables = _init_variables(model, handle)
    state_h = eng_h.init_state(variables)
    state_i = eng_i.init_state(
        jax.tree_util.tree_map(np.asarray, variables))

    for rb_h, rb_i in zip(loader_h.epoch_rounds(plan, epoch=0),
                          loader_i.epoch_index_rounds(plan, epoch=0)):
        smask = (rb_h.sample_mask * rb_h.step_mask[:, :, None]
                 * rb_h.worker_mask[:, None, None])
        sg = TrainJob._to_global(smask)
        batch_g = jax.tree_util.tree_map(TrainJob._to_global, rb_h.batch)
        state_h, losses_h = eng_h.train_steps(
            state_h, batch_g, sg, rb_h.rngs[0], lr=0.1, epoch=0)
        idx_g = TrainJob._to_global(rb_i.batch["idx"])
        state_i, losses_i = eng_i.train_steps_indexed(
            state_i, cache, idx_g, sg, rb_i.rngs[0], lr=0.1, epoch=0)
        assert np.array_equal(np.asarray(losses_h), np.asarray(losses_i))
    assert _tree_equal(eng_h.variables(state_h), eng_i.variables(state_i))


def test_syncdp_indexed_requires_replicated(setup):
    from kubeml_tpu.parallel.syncdp import SyncDPEngine

    reg, handle, model, mesh = setup
    loader = RoundLoader(handle, ToyDataset(), n_lanes=8, seed=1)
    plan = loader.plan(4, k=2, batch_size=32)
    W, _, _ = loader.round_geometry(plan)
    cache = DeviceDatasetCache(handle, mesh, layout="sharded")
    cache.ensure(plan, W)
    eng = SyncDPEngine(mesh, model.loss, model.configure_optimizers)
    eng.init_state(_init_variables(model, handle))
    with pytest.raises(ValueError, match="replicated"):
        eng.train_steps_indexed(None, cache, np.zeros((2, 256), np.int32),
                                np.ones((2, 256), np.float32),
                                np.zeros((2, 2), np.uint32), 0.1, 0)


def _make_task(epochs=2, parallelism=2, device_cache="auto",
               device_cache_mb=512, engine="kavg", shuffle=False):
    req = TrainRequest(
        model_type="mlp", batch_size=32, epochs=epochs, dataset="blobs",
        lr=0.1, options=TrainOptions(
            default_parallelism=parallelism, static_parallelism=True,
            validate_every=1, k=2, goal_accuracy=100.0, engine=engine,
            shuffle=shuffle, device_cache=device_cache,
            device_cache_mb=device_cache_mb))
    return TrainTask(job_id="cachejob1", parameters=req,
                     parallelism=parallelism)


def test_job_selects_cache_and_trains(setup):
    """Default 'auto' on an eligible, in-budget job takes the cached
    path end to end (and still learns)."""
    reg, handle, model, mesh = setup
    job = TrainJob(_make_task(), model, ToyDataset(), mesh, registry=reg)
    record = job.train()
    assert job._device_cache is not None
    assert job._device_cache.layout == "sharded"
    assert len(record.data.train_loss) == 2
    assert record.data.train_loss[-1] < record.data.train_loss[0]


def test_job_over_budget_falls_back_to_host_staging(setup):
    """'auto' with a 0 MB budget must fall back to host staging and
    train normally — the acceptance fallback trigger."""
    reg, handle, model, mesh = setup
    job = TrainJob(_make_task(device_cache_mb=0), model, ToyDataset(),
                   mesh, registry=reg)
    record = job.train()
    assert job._device_cache is None
    assert len(record.data.train_loss) == 2


def test_job_cache_off_and_ineligible_transform(setup):
    reg, handle, model, mesh = setup
    job = TrainJob(_make_task(device_cache="off"), model, ToyDataset(),
                   mesh, registry=reg)
    job.train()
    assert job._device_cache is None
    # non-identity transform without a device twin: auto silently
    # falls back...
    job2 = TrainJob(_make_task(), model, ScaledDataset(), mesh,
                    registry=reg)
    job2.train()
    assert job2._device_cache is None
    # ...but forcing it is a client error
    job3 = TrainJob(_make_task(device_cache="on"), model, ScaledDataset(),
                    mesh, registry=reg)
    with pytest.raises(KubeMLException):
        job3.train()


def test_job_syncdp_cache_replicated(setup):
    reg, handle, model, mesh = setup
    job = TrainJob(_make_task(engine="syncdp"), model, ToyDataset(),
                   mesh, registry=reg)
    record = job.train()
    assert job._device_cache is not None
    assert job._device_cache.layout == "replicated"
    assert len(record.data.train_loss) == 2


# ---------------------------------------------------------- satellites


def test_load_checkpoint_missing_fast_fails(tmp_path, tmp_home):
    """A checkpoint that never existed must raise immediately — no
    50 ms publish-race retry on the common not-found path."""
    from kubeml_tpu.train.checkpoint import load_checkpoint

    t0 = time.perf_counter()
    with pytest.raises(JobNotFoundError):
        load_checkpoint("never-existed")
    assert time.perf_counter() - t0 < 0.04


def test_infer_batcher_evicts_stale_arrival_keys():
    from kubeml_tpu.control.ps import InferBatcher

    b = InferBatcher(window_s=0.001)
    run = lambda stacked: stacked  # noqa: E731
    b.submit(("m1", (2,)), np.zeros((1, 2), np.float32), run)
    assert ("m1", (2,)) in b._last_arrival
    # age the entry past the dense-traffic horizon and re-arm the sweep
    b._last_arrival[("m1", (2,))] -= 10.0
    b._next_evict = 0.0
    b.submit(("m2", (2,)), np.zeros((1, 2), np.float32), run)
    assert ("m1", (2,)) not in b._last_arrival
    assert ("m2", (2,)) in b._last_arrival
