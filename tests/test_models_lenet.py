"""LeNet + engine integration: a few sync rounds must reduce loss on a
learnable synthetic MNIST-like task."""

import jax
import jax.numpy as jnp
import numpy as np

from kubeml_tpu.models import get_builtin
from kubeml_tpu.parallel.kavg import KAvgEngine


def make_fake_mnist(rng, n):
    """Class-dependent blobs: class c lights up a cth patch."""
    y = rng.randint(0, 10, size=n)
    x = rng.rand(n, 28, 28).astype(np.float32) * 0.1
    for i in range(n):
        c = y[i]
        x[i, (c * 2):(c * 2 + 4), 2:18] += 1.0
    return x, y.astype(np.int32)


def test_lenet_learns(mesh8):
    rng = np.random.RandomState(0)
    model = get_builtin("lenet")()
    W, S, B = 8, 2, 16
    x, y = make_fake_mnist(rng, W * S * B)
    xs = x.reshape(W, S, B, 28, 28)
    ys = y.reshape(W, S, B)

    variables = model.init_variables(
        jax.random.PRNGKey(0), {"x": jnp.asarray(xs[0, 0])})
    engine = KAvgEngine(mesh8, model.loss, model.metrics,
                        model.configure_optimizers)

    batch = {"x": jnp.asarray(xs), "y": jnp.asarray(ys)}
    masks = dict(sample_mask=np.ones((W, S, B)), step_mask=np.ones((W, S)),
                 worker_mask=np.ones(W))
    first_loss = None
    for round_i in range(8):
        rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)
        variables, stats = engine.train_round(
            variables, batch, rngs=rngs, lr=0.1, epoch=0, **masks)
        mean_loss = stats.loss_sum.sum() / stats.step_count.sum()
        if first_loss is None:
            first_loss = mean_loss
    assert mean_loss < first_loss * 0.7, (first_loss, mean_loss)

    out = engine.eval_round(variables, batch, masks["sample_mask"])
    assert out["accuracy"] > 0.3  # way above 10% chance
    preds = model.infer(variables, xs[0, 0])
    assert preds.shape == (B,)
