"""Serving-fleet tests (kubeml_tpu/serve/fleet.py + the wiring around it).

The contracts pinned here:

  * router identity — a stream routed through the fleet (affinity hit,
    spill, cold start) decodes TOKEN-FOR-TOKEN identically to the same
    request on a solo engine; every FLEET_PATH_VARIANTS entry is named
    next to an exactness assertion (tools/check_fleet_paths.py lints
    that this stays true)
  * shed handling — a shed on the affine replica is retried once
    against a peer; a surfaced shed carries the FLEET-minimum
    Retry-After; a single-replica fleet passes the replica's shed
    through verbatim
  * lifecycle — shrink drains its victim through the grace path and
    loses zero in-flight streams; scale-to-zero → cold-start → serve
    round-trips, at the fleet level and e2e through POST /generate
  * pool sharing — serving gangs ride the cluster allocator's Decision
    machinery ("serve-elastic" path) via the scheduler's /serve/resize,
    and never park
  * telemetry — per-replica prefix hit/miss deltas in the fleet
    snapshot, the Prometheus fleet families pass the metrics lint, and
    `kubeml top` renders the fleet pane
"""

import json
import time
import urllib.request

import numpy as np
import pytest

pytestmark = pytest.mark.serving


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(scope="module")
def nano():
    import jax

    from kubeml_tpu.models import get_builtin
    model = get_builtin("gpt-nano")()
    module = model.module
    variables = model.init_variables(
        jax.random.PRNGKey(0),
        {"x": np.ones((1, module.max_len), np.int32)})
    return model, module, variables


def _factory(module, variables, *, slots=2, page=4, max_queue=2):
    """index -> UNSTARTED ServeService, the fleet's replica recipe."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.service import ServeService

    def make(index):
        engine = DecodeEngine(module, variables, slots=slots, page=page)
        return ServeService("fleet-m", engine, max_queue=max_queue,
                            supervise=False)
    return make


def _solo_tokens(module, variables, prompt, n_new, *, page=4):
    """Reference decode: the same request alone on a fresh engine."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    engine = DecodeEngine(module, variables, slots=2, page=page)
    req = GenerateRequest(list(prompt), max_new_tokens=n_new)
    engine.attach(req)
    limit = 10_000
    while engine.active():
        engine.step()
        limit -= 1
        assert limit > 0, "solo engine failed to drain"
    assert req.outcome == "ok"
    return req.tokens


def _fleet(module, variables, **kw):
    from kubeml_tpu.serve.fleet import ServeFleet
    kw.setdefault("autoscale_interval_s", 0.0)   # tests drive ticks
    kw.setdefault("page_tokens", 4)
    factory_kw = {k: kw.pop(k) for k in ("slots", "max_queue")
                  if k in kw}
    return ServeFleet("fleet-m", _factory(module, variables,
                                          **factory_kw), **kw)


# ----------------------------------------------------------- routing paths


def test_affine_routing_is_sticky_and_bit_identical(nano):
    """Same-prefix requests all land on the consistent-hash owner
    ("affine_hit") and the routed streams decode exactly like a solo
    engine's — the fleet is a router, not a different decoder."""
    _model, module, variables = nano
    fleet = _fleet(module, variables, replicas_min=2, replicas_max=2)
    fleet.start()
    try:
        # same first page (page_tokens=4) -> same routing digest
        specs = [([5, 6, 7, 8, 9], 5), ([5, 6, 7, 8, 10, 11], 4),
                 ([5, 6, 7, 8, 9], 5)]
        reqs = []
        for prompt, n in specs:
            r = fleet.submit(prompt, max_new_tokens=n)
            assert r.wait(120)
            reqs.append(r)
        assert all(r.outcome == "ok" for r in reqs)
        homes = {r.fleet_replica for r in reqs}
        assert len(homes) == 1, f"affine prompts split across {homes}"
        assert fleet.path_counts["affine_hit"] >= 3
        for (prompt, n), r in zip(specs, reqs):
            np.testing.assert_array_equal(
                r.tokens, _solo_tokens(module, variables, prompt, n))
        # session stickiness overrides the ring: pin s1 to the OTHER
        # replica and the next submit follows the session, not the hash
        other = next(i for i, _ in fleet.engines() if i not in homes)
        fleet._sessions["s1"] = other
        r = fleet.submit([5, 6, 7, 8, 9], max_new_tokens=5, session="s1")
        assert r.wait(120) and r.outcome == "ok"
        assert r.fleet_replica == other
    finally:
        fleet.stop(grace_s=0.0)


def test_spill_routes_around_saturated_owner(nano):
    """A saturated ring owner spills to the least-loaded admitting peer
    ("spill") instead of shedding, and the spilled stream is still
    bit-identical to the solo engine."""
    from kubeml_tpu.serve.pager import routing_digest

    _model, module, variables = nano
    fleet = _fleet(module, variables, replicas_min=2, replicas_max=2,
                   slots=1, max_queue=0)
    fleet.start()
    try:
        prompt = [5, 6, 7, 8, 9]
        owner = fleet._ring_owner(routing_digest(prompt, 4))
        # saturate the owner (capacity 1) with a long-running stream
        busy = fleet._replicas[owner].submit([9, 10, 11],
                                             max_new_tokens=48)
        r = fleet.submit(prompt, max_new_tokens=5)
        assert r.fleet_replica != owner
        assert fleet.path_counts["spill"] >= 1
        assert fleet.spills_total >= 1
        assert r.wait(120) and r.outcome == "ok"
        np.testing.assert_array_equal(
            r.tokens, _solo_tokens(module, variables, prompt, 5))
        assert busy.wait(120)
    finally:
        fleet.stop(grace_s=0.0)


def test_random_routing_ignores_the_prompt(nano):
    """The bench control arm: routing="random" spreads identical
    prompts across replicas (given enough draws) — the property the
    affinity arm must beat on prefix-cache hit rate."""
    _model, module, variables = nano
    fleet = _fleet(module, variables, replicas_min=2, replicas_max=2,
                   routing="random")
    fleet.start()
    try:
        homes = set()
        for _ in range(8):
            r = fleet.submit([5, 6, 7, 8, 9], max_new_tokens=2)
            assert r.wait(120) and r.outcome == "ok"
            homes.add(r.fleet_replica)
        assert homes == {0, 1}
    finally:
        fleet.stop(grace_s=0.0)
    with pytest.raises(ValueError):
        _fleet(module, variables, routing="round-robin")


# ------------------------------------------------------------ shed handling


def test_surfaced_shed_carries_fleet_minimum_retry_after(nano):
    """Both replicas shed -> the router retried once, and the surfaced
    Retry-After is the FLEET minimum (the lightly-backlogged replica's
    hint), not the first replica's heavy estimate."""
    from kubeml_tpu.serve.slots import ServeSaturated

    _model, module, variables = nano
    fleet = _fleet(module, variables, replicas_min=2, replicas_max=2,
                   slots=1, max_queue=1)
    fleet.start()
    try:
        # replica 0: two 40-token prompts -> heavy prefill backlog
        # (shed hint ~= 1 + 78/256 s); replica 1: two 3-token prompts
        # -> hint ~= 1.0 s. capacity is 2 each, so the fleet is full.
        heavy = [fleet._replicas[0].submit(list(range(1, 41)),
                                           max_new_tokens=8)
                 for _ in range(2)]
        light = [fleet._replicas[1].submit([5, 6, 7], max_new_tokens=32)
                 for _ in range(2)]
        with pytest.raises(ServeSaturated) as ei:
            fleet.submit([5, 6, 7, 8, 9], max_new_tokens=4)
        assert "fleet at capacity" in ei.value.message
        assert fleet.router_retries_total == 1
        assert 1.0 <= ei.value.retry_after_s < 1.2   # min, not ~1.3
        assert heavy and light                       # keep refs alive
    finally:
        fleet.stop(grace_s=0.0)


def test_single_replica_shed_passes_through_verbatim(nano):
    """With one replica and no peers there is nothing router-aware to
    say: the replica's own exception surfaces unwrapped, preserving the
    exact Retry-After contract the solo-service tests pin."""
    from kubeml_tpu.serve.slots import ServeSaturated

    _model, module, variables = nano
    fleet = _fleet(module, variables, replicas_min=1, replicas_max=1,
                   slots=1, max_queue=0)
    fleet.start()
    try:
        busy = fleet._replicas[0].submit([9, 10, 11], max_new_tokens=48)
        with pytest.raises(ServeSaturated) as ei:
            fleet.submit([5, 6, 7, 8, 9], max_new_tokens=4)
        assert "fleet at capacity" not in (ei.value.message or "")
        assert fleet.router_retries_total == 0
        assert busy.wait(120)
    finally:
        fleet.stop(grace_s=0.0)


# --------------------------------------------------------------- lifecycle


def test_shrink_drains_victim_without_losing_streams(nano):
    """Retiring a replica ("shrink_drain") goes off the ring first,
    then through the grace drain: the in-flight stream on the victim
    finishes normally and matches the solo engine bit-for-bit."""
    _model, module, variables = nano
    fleet = _fleet(module, variables, replicas_min=1, replicas_max=2,
                   drain_grace_s=120.0)
    fleet.start()
    fleet._spawn_one()
    try:
        assert fleet.replica_count == 2
        prompt = [5, 6, 7, 8, 9]
        r = fleet.submit(prompt, max_new_tokens=6)
        victim = r.fleet_replica
        # retire the replica that is mid-stream: drain must wait it out
        assert fleet._retire(victim, "shrink_drain") is True
        assert r.outcome == "ok", "shrink lost an in-flight stream"
        np.testing.assert_array_equal(
            r.tokens, _solo_tokens(module, variables, prompt, 6))
        assert fleet.replica_count == 1
        assert fleet.path_counts["shrink_drain"] == 1
    finally:
        fleet.stop(grace_s=0.0)


@pytest.mark.slo
def test_autoscaler_burn_rate_grow_and_idle_window_expiry(nano):
    """The burn-rate autoscaler end to end: one SLO-bad request burns
    both windows above 1.0 -> alert onset -> grow. While the alert is
    still inside its fast window an idle fleet holds (no shrink/grow
    flap); once the bad tick ages out, burn drains to zero ON ITS OWN
    and sustained idleness shrinks back to the floor — after which an
    idle fleet never grows again. The old instantaneous-p99 path (and
    its `inflight > 0` staleness guard) is gone: the signal expires
    with the window instead of being special-cased."""
    from kubeml_tpu.serve.fleet import SHRINK_IDLE_TICKS
    from kubeml_tpu.serve.slo import FAST_WINDOW_TICKS

    _model, module, variables = nano
    # a TTFT objective no real decode can meet: every completed request
    # classifies "bad", making the burn signal deterministic on CPU
    fleet = _fleet(module, variables, replicas_min=1, replicas_max=2,
                   slo_ttft_s=1e-9)
    fleet.start()
    try:
        r = fleet.submit([5, 6, 7, 8], max_new_tokens=2)
        assert r.wait(120) and r.outcome == "ok"
        # the in-flight count decrements on the loop thread just after
        # the request goes terminal: wait for true quiescence
        deadline = time.time() + 30
        while any(s.inflight for s in fleet.replicas()) \
                and time.time() < deadline:
            time.sleep(0.01)
        # tick 1 folds the bad request into the windows: burn > 1.0 in
        # BOTH -> alert onset -> burn-driven grow (not a shed in sight)
        assert fleet.autoscale_once() == "grow"
        assert fleet.replica_count == 2
        assert fleet.grows_total == 1
        assert any(d["action"] == "slo_burn" for d in fleet.decisions)
        snap = fleet.snapshot()
        assert snap["serve_slo_bad_total"] == 1
        assert snap["serve_slo_good_total"] == 0
        assert snap["serve_slo_alerts_total"] == 1
        assert snap["serve_slo_burn_fast"] > 1.0
        assert snap["serve_slo_burn_slow"] > 1.0
        assert snap["serve_slo_attainment"] == 0.0
        # while the bad tick is inside the fast window the idle fleet
        # holds: at the cap so no grow, still alerting so no shrink
        for _ in range(FAST_WINDOW_TICKS - 1):
            assert fleet.autoscale_once() is None
        assert fleet.replica_count == 2
        # the window has EXPIRED: the fast burn is zero without any
        # special-casing, and accumulated idleness shrinks to the floor
        assert fleet.autoscale_once() == "shrink"
        assert fleet.replica_count == 1
        assert fleet.shrinks_total == 1
        assert fleet.snapshot()["serve_slo_burn_fast"] == 0.0
        # at the floor with expired windows: idleness never grows
        for _ in range(FAST_WINDOW_TICKS + SHRINK_IDLE_TICKS):
            assert fleet.autoscale_once() is None
        assert fleet.replica_count == 1
        assert fleet.grows_total == 1
    finally:
        fleet.stop(grace_s=0.0)


def test_autoscale_tick_publishes_merged_snapshot_on_alert_flips(nano):
    """Burn state moves only on the autoscale tick, and replicas publish
    only while active — so the tick must push the merged snapshot when
    the burn alert FLIPS, or a fleet that goes idle right after its bad
    requests leaves the health/metrics surfaces frozen at the pre-tick
    SLO values (bad counted, burn still zero) until the next request.
    And ONLY on the flips: an every-tick merged publish contends with
    the router for the fleet lock under load."""
    from kubeml_tpu.serve.slo import FAST_WINDOW_TICKS

    _model, module, variables = nano
    fleet = _fleet(module, variables, replicas_min=1, replicas_max=1,
                   slo_ttft_s=1e-9)
    published = []
    fleet.health_cb = published.append
    fleet.start()
    try:
        r = fleet.submit([5, 6, 7, 8], max_new_tokens=2)
        assert r.wait(120) and r.outcome == "ok"
        deadline = time.time() + 30
        while any(s.inflight for s in fleet.replicas()) \
                and time.time() < deadline:
            time.sleep(0.01)
        published.clear()                 # drop the in-flight publishes
        # onset tick: alert flips ON -> exactly one tick-driven publish
        # carrying the tick-fresh burn state (at the cap, so no grow —
        # the flip publish must not depend on a scale action happening)
        assert fleet.autoscale_once() is None
        assert [p["serve_slo_burn_fast"] for p in published] == [100.0]
        assert published[0]["serve_slo_burn_slow"] == 100.0
        assert published[0]["serve_slo_attainment"] == 0.0
        assert published[0]["serve_slo_alerts_total"] == 1
        published.clear()
        # alert steady inside both windows: no flip, no publish
        for _ in range(FAST_WINDOW_TICKS - 1):
            assert fleet.autoscale_once() is None
        assert published == []
        # the fast window expires: alert flips OFF -> one recovery
        # publish so the surfaces show the burn draining
        assert fleet.autoscale_once() is None
        assert [p["serve_slo_burn_fast"] for p in published] == [0.0]
    finally:
        fleet.stop(grace_s=0.0)


def test_autoscaler_grows_on_shed_pressure(nano):
    """A shed since the last tick grows the fleet (allocator grant
    permitting) toward replicas_max, and the grant flow records the
    offered counts."""
    from kubeml_tpu.serve.slots import ServeSaturated

    _model, module, variables = nano
    offers = []

    def grant(n):
        offers.append(n)
        return n

    fleet = _fleet(module, variables, replicas_min=1, replicas_max=2,
                   slots=1, max_queue=0, resize_cb=grant)
    fleet.start()
    try:
        busy = fleet._replicas[0].submit([9, 10, 11], max_new_tokens=48)
        with pytest.raises(ServeSaturated):
            fleet.submit([5, 6, 7, 8], max_new_tokens=2)
        assert fleet.autoscale_once() == "grow"
        assert fleet.replica_count == 2
        assert fleet.grows_total == 1
        assert offers[-1] == 2
        assert busy.wait(120)
    finally:
        fleet.stop(grace_s=0.0)


def test_autoscaler_respects_denied_grant(nano):
    """The allocator said no: the fleet stays put and re-asks on the
    next tick instead of exceeding its grant."""
    from kubeml_tpu.serve.slots import ServeSaturated

    _model, module, variables = nano
    fleet = _fleet(module, variables, replicas_min=1, replicas_max=2,
                   slots=1, max_queue=0, resize_cb=lambda n: 1)
    fleet.start()
    try:
        busy = fleet._replicas[0].submit([9, 10, 11], max_new_tokens=48)
        with pytest.raises(ServeSaturated):
            fleet.submit([5, 6, 7, 8], max_new_tokens=2)
        assert fleet.autoscale_once() is None
        assert fleet.replica_count == 1
        assert busy.wait(120)
    finally:
        fleet.stop(grace_s=0.0)


def test_scale_to_zero_and_cold_start_round_trip(nano):
    """The serverless loop at fleet level: idle past the budget drains
    the fleet away ("scale_to_zero"), the next request cold-starts
    replica 0 synchronously ("cold_start") and is served — with tokens
    identical to a solo engine's — while concurrent arrivals during the
    warm-up shed with the remaining estimate."""
    from kubeml_tpu.serve.slots import ServeSaturated

    _model, module, variables = nano
    clock = FakeClock()
    fleet = _fleet(module, variables, replicas_min=0, replicas_max=1,
                   scale_to_zero_s=5.0, clock=clock)
    fleet.start()
    try:
        assert fleet.replica_count == 0       # min=0 starts EMPTY
        prompt = [5, 6, 7, 8, 9]
        r = fleet.submit(prompt, max_new_tokens=5)
        assert fleet.path_counts["cold_start"] == 1
        assert r.wait(120) and r.outcome == "ok"
        np.testing.assert_array_equal(
            r.tokens, _solo_tokens(module, variables, prompt, 5))

        deadline = time.time() + 30           # loop-thread bookkeeping
        while any(s.inflight for s in fleet.replicas()) \
                and time.time() < deadline:
            time.sleep(0.01)
        clock.advance(10.0)                   # idle past the budget
        assert fleet.autoscale_once() == "scale_to_zero"
        assert fleet.replica_count == 0
        assert fleet.path_counts["scale_to_zero"] == 1

        # a request that lands WHILE a cold start is mid-build sheds
        # with the remaining warm estimate instead of dogpiling
        fleet._warming = True
        fleet._warm_started = clock()
        with pytest.raises(ServeSaturated) as ei:
            fleet.submit(prompt, max_new_tokens=2)
        assert ei.value.retry_after_s > 0
        fleet._warming = False

        r2 = fleet.submit(prompt, max_new_tokens=5)
        assert fleet.cold_starts_total == 2
        assert r2.wait(120) and r2.outcome == "ok"
        np.testing.assert_array_equal(r2.tokens, r.tokens)
    finally:
        fleet.stop(grace_s=0.0)


def test_generate_scale_to_zero_cold_start_round_trip_e2e(tmp_home):
    """E2e through POST /generate: a fleet with replicas_min=0 scales
    itself to zero after the idle budget, and the next HTTP request
    cold-starts and returns the same tokens as before."""
    import jax

    from kubeml_tpu.control.ps import ParameterServer
    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.train.checkpoint import save_checkpoint

    model = get_builtin("gpt-nano")()
    variables = model.init_variables(
        jax.random.PRNGKey(0),
        {"x": np.ones((1, model.module.max_len), np.int32)})
    save_checkpoint("fleetnano", variables,
                    {"model": "gpt-nano", "function": "gpt-nano",
                     "parallelism": 1, "epoch": 0})
    ps = ParameterServer(serve_slots=2, serve_queue_depth=1,
                         serve_replicas_min=0, serve_replicas_max=1,
                         serve_scale_to_zero_s=0.2)
    ps.start()
    try:
        body = {"model_id": "fleetnano", "prompt": [5, 6, 7, 8],
                "max_new_tokens": 4, "stream": False}

        def generate():
            req = urllib.request.Request(
                f"{ps.url}/generate", data=json.dumps(body).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            return json.loads(urllib.request.urlopen(
                req, timeout=120).read())["tokens"]

        first = generate()                    # cold start #1 (min=0)
        with ps._serve_lock:
            fleet = ps._serve["fleetnano"][1]
        assert fleet.path_counts["cold_start"] >= 1
        deadline = time.time() + 60
        while fleet.replica_count > 0 and time.time() < deadline:
            time.sleep(0.05)
        assert fleet.replica_count == 0, "fleet never scaled to zero"
        assert fleet.scale_to_zero_total >= 1
        second = generate()                   # cold start #2
        np.testing.assert_array_equal(second, first)
        assert fleet.cold_starts_total >= 2
    finally:
        ps.stop()


def test_fleet_drain_flips_every_replica(nano):
    """Fleet drain = PR-12 drain on every replica at once; afterwards
    admission sheds as stopped."""
    from kubeml_tpu.serve.slots import ServeSaturated

    _model, module, variables = nano
    fleet = _fleet(module, variables, replicas_min=2, replicas_max=2)
    fleet.start()
    try:
        assert fleet.drain(5.0) is True
        with pytest.raises(ServeSaturated):
            fleet.submit([5, 6, 7, 8], max_new_tokens=2)
    finally:
        fleet.stop(grace_s=0.0)


# --------------------------------------------------------------- telemetry


def test_fleet_snapshot_per_replica_prefix_deltas(nano):
    """The fleet snapshot exposes per-replica prefix hit/miss DELTAS
    since the previous snapshot: a repeat of a routed prefix shows up
    as a hit on the affine replica and zeros elsewhere."""
    _model, module, variables = nano
    fleet = _fleet(module, variables, replicas_min=2, replicas_max=2)
    fleet.start()
    try:
        # silence the background replica publishes so only OUR snapshot
        # calls consume the deltas (deterministic cursors)
        for svc in fleet.replicas():
            svc.health_cb = None
        prompt = [5, 6, 7, 8, 9]
        r1 = fleet.submit(prompt, max_new_tokens=2)
        assert r1.wait(120) and r1.outcome == "ok"
        fleet.snapshot()                      # absorb the first round
        r2 = fleet.submit(prompt, max_new_tokens=2)
        assert r2.wait(120) and r2.outcome == "ok"
        assert r2.fleet_replica == r1.fleet_replica
        snap = fleet.snapshot()
        home, other = str(r1.fleet_replica), str(
            1 - r1.fleet_replica)
        assert snap["fleet_replica_prefix_hits"][home] >= 1
        assert snap["fleet_replica_prefix_hits"][other] == 0
        assert snap["fleet_replica_prefix_misses"][other] == 0
        assert snap["job_id"] == "serve:fleet-m"
        assert snap["fleet_replicas"] == 2
        assert snap["serve_slot_cap"] == 4    # summed across replicas
    finally:
        fleet.stop(grace_s=0.0)


def _events_by(tracer, name, trace_id=None):
    return [e for e in tracer.events() if e["name"] == name
            and (trace_id is None
                 or e["args"].get("trace_id") == trace_id)]


def _submit_when_free(svc, prompt, max_new_tokens, timeout_s=30.0):
    """Direct-replica submit that tolerates the slot of a just-finished
    request still draining in the serving loop."""
    from kubeml_tpu.serve.slots import ServeSaturated

    deadline = time.time() + timeout_s
    while True:
        try:
            return svc.submit(prompt, max_new_tokens=max_new_tokens)
        except ServeSaturated:
            assert time.time() < deadline, "replica never freed a slot"
            time.sleep(0.01)


@pytest.mark.slo
def test_fleet_router_stitches_routing_spans_onto_request_trace(nano):
    """FLEET_SPAN_KINDS on the request timeline: every routing
    decision the fleet makes lands on the request's trace parented to
    its "generate" root and carrying the client trace_id — an affine
    hit, a proactive spill around a saturated owner, and the
    retry-after-shed instant when every replica sheds."""
    from kubeml_tpu.serve.pager import routing_digest
    from kubeml_tpu.serve.slots import ServeSaturated
    from kubeml_tpu.utils.trace import Tracer

    _model, module, variables = nano
    tracer = Tracer()
    fleet = _fleet(module, variables, replicas_min=2, replicas_max=2,
                   slots=1, max_queue=0, tracer=tracer)
    fleet.start()
    try:
        prompt = [5, 6, 7, 8, 9]
        with fleet._lock:
            owner = fleet._ring_owner(routing_digest(prompt, 4))
        r1 = fleet.submit(prompt, max_new_tokens=2,
                          trace_id="t-affine")
        assert r1.wait(120) and r1.outcome == "ok"
        (route,) = _events_by(tracer, "route", "t-affine")
        assert route["args"]["parent"] == "generate"
        assert route["args"]["replica"] == owner
        assert route["args"]["path"] == "affine_hit"
        assert route["args"]["rid"] == r1.rid
        assert route["dur"] >= 0
        hit = _events_by(tracer, "affine_hit", "t-affine")
        assert hit, 'missing "affine_hit" instant'
        assert hit[0]["args"]["replica"] == owner

        # saturate the owner (capacity 1): the same prompt now spills
        busy = _submit_when_free(fleet._replicas[owner], [9, 10, 11],
                                 48)
        r2 = fleet.submit(prompt, max_new_tokens=2, trace_id="t-spill")
        assert r2.wait(120) and r2.outcome == "ok"
        spill = _events_by(tracer, "spill", "t-spill")
        assert spill and spill[0]["args"]["replica"] != owner
        (route2,) = _events_by(tracer, "route", "t-spill")
        assert route2["args"]["path"] == "spill"

        # saturate BOTH replicas with freshly started streams (the
        # first busy stream may have drained during r2's generate),
        # then submit: the routed retry leaves its "retry" instant on
        # the trace before the fleet surfaces the shed
        assert busy.wait(120)
        busy1 = _submit_when_free(fleet._replicas[0], [9, 10, 11], 48)
        busy2 = _submit_when_free(fleet._replicas[1], [9, 10, 12], 48)
        with pytest.raises(ServeSaturated):
            fleet.submit(prompt, max_new_tokens=2, trace_id="t-shed")
        retry = _events_by(tracer, "retry", "t-shed")
        assert retry, 'missing "retry" instant'
        assert retry[0]["args"]["parent"] == "generate"
        assert retry[0]["args"]["shed_replica"] in (0, 1)
        assert busy1.wait(120) and busy2.wait(120)
    finally:
        fleet.stop(grace_s=0.0)


@pytest.mark.slo
def test_cold_start_wait_span_covers_the_build(nano):
    """A scale-from-zero submit's trace shows WHERE the latency went:
    a "cold_start_wait" span covering the synchronous replica build,
    parented to the same "generate" root as the route span."""
    from kubeml_tpu.utils.trace import Tracer

    _model, module, variables = nano
    tracer = Tracer()
    fleet = _fleet(module, variables, replicas_min=0, replicas_max=1,
                   tracer=tracer)
    fleet.start()
    try:
        assert fleet.replica_count == 0
        r = fleet.submit([5, 6, 7, 8], max_new_tokens=2,
                         trace_id="t-cold")
        assert r.wait(120) and r.outcome == "ok"
        (wait_span,) = _events_by(tracer, "cold_start_wait", "t-cold")
        assert wait_span["name"] == "cold_start_wait"
        assert wait_span["args"]["parent"] == "generate"
        assert wait_span["args"]["replica"] == r.fleet_replica
        assert wait_span["dur"] > 0          # the build took real time
        (route,) = _events_by(tracer, "route", "t-cold")
        assert route["ts"] >= wait_span["ts"]
    finally:
        fleet.stop(grace_s=0.0)


@pytest.mark.slo
def test_fleet_snapshot_merges_replica_sketches_exactly(nano):
    """Fleet percentiles come from MERGED windowed sketches: the
    snapshot's TTFT sketch equals — bucket for bucket — the merge of
    the per-replica sketch states, and p50/p99 are read off that
    merged sketch (not a worst-replica heuristic)."""
    from kubeml_tpu.metrics.sketch import QuantileSketch

    _model, module, variables = nano
    fleet = _fleet(module, variables, replicas_min=2, replicas_max=2,
                   routing="random")
    fleet.start()
    try:
        reqs = [fleet.submit([5, 6, 7, 8, 9], max_new_tokens=2)
                for _ in range(6)]
        for r in reqs:
            assert r.wait(120) and r.outcome == "ok"
        deadline = time.time() + 30
        while any(s.inflight for s in fleet.replicas()) \
                and time.time() < deadline:
            time.sleep(0.01)
        pooled = QuantileSketch()
        for svc in fleet.replicas():
            state = svc.snapshot()["serve_latency_sketches"]["ttft"]
            pooled.merge(QuantileSketch.from_state(state))
        assert pooled.count == 6
        snap = fleet.snapshot()
        assert snap["serve_latency_sketches"]["ttft"] == pooled.state()
        assert snap["serve_ttft_p50"] == round(pooled.quantile(0.50), 6)
        assert snap["serve_ttft_p99"] == round(pooled.quantile(0.99), 6)
        assert 0 < snap["serve_ttft_p50"] <= snap["serve_ttft_p99"]
    finally:
        fleet.stop(grace_s=0.0)


def test_fleet_metrics_families_pass_lint():
    """update_fleet mirrors a merged snapshot into the fleet families
    (per-replica series via the `replica` LABEL, counters by delta) and
    the exposition passes the metrics lint."""
    from kubeml_tpu.metrics.prom import MetricsRegistry
    from tools.check_metrics import validate_exposition

    reg = MetricsRegistry()
    snap = {"fleet_replicas": 3, "fleet_spills_total": 2,
            "fleet_router_retries_total": 1, "fleet_cold_starts_total": 1,
            "fleet_grows_total": 2, "fleet_shrinks_total": 1,
            "fleet_scale_to_zero_total": 0,
            "fleet_replica_prefix_hits": {"0": 4, "1": 0},
            "fleet_replica_prefix_misses": {"0": 1, "1": 2}}
    reg.update_fleet("m1", snap)
    # a republish with unchanged cumulative counters and drained deltas
    # (what a real steady-state snapshot looks like) adds nothing
    reg.update_fleet("m1", dict(snap, fleet_replica_prefix_hits={},
                                fleet_replica_prefix_misses={}))
    text = reg.exposition()
    assert 'kubeml_serve_fleet_replicas{model="m1"} 3' in text
    assert 'kubeml_serve_fleet_spills_total{model="m1"} 2' in text
    assert ('kubeml_serve_fleet_scale_events_total'
            '{model="m1",action="grow"} 2') in text
    assert ('kubeml_serve_fleet_replica_prefix_hits_total'
            '{model="m1",replica="0"} 4') in text
    assert validate_exposition(text) == []
    reg.clear_serve("m1")
    assert 'model="m1"' not in reg.exposition()


def test_top_renders_fleet_pane():
    from kubeml_tpu.cli.main import _render_top

    doc = {"id": "serve:m1", "state": "healthy", "reasons": [],
           "latest": {"serve_active_slots": 2, "serve_slot_cap": 8,
                      "serve_queue_depth": 0, "serve_queue_cap": 16,
                      "serve_kv_page_utilization": 0.25,
                      "serve_rejected_total": 0,
                      "serve_ttft_p50": 0.010, "serve_ttft_p99": 0.020,
                      "fleet_replicas": 2, "fleet_replicas_min": 1,
                      "fleet_replicas_max": 4, "fleet_draining": 0,
                      "fleet_spills_total": 3,
                      "fleet_router_retries_total": 1,
                      "fleet_cold_starts_total": 2,
                      "fleet_grows_total": 5, "fleet_shrinks_total": 4,
                      "fleet_scale_to_zero_total": 1}}
    out = _render_top(doc)
    assert "fleet: replicas 2 [1..4]" in out
    assert "spills 3" in out
    assert "cold starts 2" in out
    assert "grow/shrink/zero 5/4/1" in out
    # a solo-service snapshot (no fleet_replicas) has no fleet line
    del doc["latest"]["fleet_replicas"]
    assert "fleet:" not in _render_top(doc)


# ----------------------------------------------------- pool sharing (cluster)


def test_cluster_serving_gang_kind_and_serve_elastic_path():
    """Serving replicas are the allocator's second gang kind: they
    place/resize through the same Decision machinery, resizes are
    tagged with the "serve-elastic" path, and the snapshot breaks out
    serving jobs/lanes."""
    from kubeml_tpu.control.cluster import (DECISION_PATHS,
                                            ClusterAllocator)

    assert "serve-elastic" in DECISION_PATHS
    alloc = ClusterAllocator(4, clock=FakeClock())
    (d,) = alloc.submit("serve:m1", lanes=1, kind="serving")
    assert d.action == "place" and d.lanes == 1
    ds = alloc.resize("serve:m1", 2)
    assert ds[0].action == "resize" and ds[0].lanes == 2
    assert ds[0].path == "serve-elastic"
    snap = alloc.snapshot()
    assert snap["cluster_serving_jobs"] == 1
    assert snap["cluster_serving_lanes"] == 2
    assert alloc.running_lanes("serve:m1") == 2
    # training resizes keep their own paths
    alloc.submit("train0001", lanes=1)
    tds = alloc.resize("train0001", 2)
    assert tds[0].path != "serve-elastic"
    assert alloc.running_lanes("nope") is None


def test_scheduler_serve_resize_grows_shrinks_and_never_parks():
    """/serve/resize: grow places a serving gang, shrink-to-zero frees
    its lanes, a full pool answers granted=0 WITHOUT parking (the
    fleet's next tick re-asks), and a scheduler without an allocator
    fails open."""
    from kubeml_tpu.control.cluster import ClusterAllocator
    from kubeml_tpu.control.httpd import Request
    from kubeml_tpu.control.scheduler import Scheduler

    def resize(body):
        return Request(path="/serve/resize", params={}, query={},
                       body=body, raw=b"")

    alloc = ClusterAllocator(4, clock=FakeClock())
    sched = Scheduler(ps_url=None, allocator=alloc)  # handlers inline
    out = sched._h_serve_resize(resize({"model_id": "m1", "replicas": 2}))
    assert out == {"granted": 2}
    assert alloc.running_lanes("serve:m1") == 2
    # grow past the pool clamps to what fits
    out = sched._h_serve_resize(resize({"model_id": "m1", "replicas": 8}))
    assert out == {"granted": 4}
    # scale to zero frees every lane
    out = sched._h_serve_resize(resize({"model_id": "m1", "replicas": 0}))
    assert out == {"granted": 0}
    assert alloc.running_lanes("serve:m1") is None
    # pool full of training work: the serving gang is granted 0 and
    # does NOT hold a queue slot against later arrivals
    alloc.submit("train0001", lanes=4)
    out = sched._h_serve_resize(resize({"model_id": "m1", "replicas": 1}))
    assert out == {"granted": 0}
    assert alloc.running_lanes("serve:m1") is None
    assert alloc.snapshot()["cluster_queue_depth"] == 0
    # no allocator: fail open so elasticity never stalls
    bare = Scheduler(ps_url=None)
    assert bare._h_serve_resize(
        resize({"model_id": "m1", "replicas": 3})) == {"granted": 3}


# ----------------------------------------------------------------- the lint


def test_check_fleet_paths_lint_passes_on_repo():
    """The lint itself, run over the real tree: every registered fleet
    path variant is covered by this file's tests."""
    import os

    from kubeml_tpu.serve.fleet import FLEET_PATH_VARIANTS
    from tools.check_fleet_paths import main, path_variants

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fleet_path = os.path.join(root, "kubeml_tpu", "serve", "fleet.py")
    assert tuple(path_variants(fleet_path)) == FLEET_PATH_VARIANTS
    assert main(["check_fleet_paths.py", root]) == 0


def test_check_fleet_paths_lint_selftest(tmp_path):
    """The lint catches an uncovered variant, ignores comment-only
    mentions, and fails loudly when the registry is missing."""
    from tools.check_fleet_paths import main, uncovered_variants

    fleet_dir = tmp_path / "kubeml_tpu" / "serve"
    fleet_dir.mkdir(parents=True)
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    fleet = fleet_dir / "fleet.py"
    fleet.write_text(
        'FLEET_PATH_VARIANTS = (\n    "covered_path",\n'
        '    "naked_path",\n)\n')
    (tests_dir / "test_ok.py").write_text(
        'import numpy as np\n'
        'def test_covered():\n'
        '    # naked_path mentioned in a comment only: does not count\n'
        '    variant = "covered_path"\n'
        '    np.testing.assert_array_equal([1], [1])\n')
    assert uncovered_variants(str(fleet), str(tests_dir)) == ["naked_path"]
    assert main(["lint", str(tmp_path)]) == 1
    (tests_dir / "test_fix.py").write_text(
        'import numpy as np\n'
        'def test_naked():\n'
        '    assert "naked_path"\n'
        '    np.testing.assert_array_equal([2], [2])\n')
    assert main(["lint", str(tmp_path)]) == 0
    fleet.write_text("FLEET_PATH_VARIANTS = ()\n")
    assert main(["lint", str(tmp_path)]) == 1
