"""Inference-plane tests (kubeml_tpu/serve/ + the PS /generate route).

The contracts pinned here are the ones the subsystem is built around:

  * bit-identity — a request generates the SAME tokens continuously
    batched with neighbours as it does running alone (slot math is
    row-independent, pages disjoint, sampling keys per (seed, pos))
  * compile pinning — joins/leaves/EOS churn slot membership as DATA;
    the decode program compiles exactly once per engine
    (JitCompileTracker), never per membership change
  * page accounting — KV pages free on EOS/cancel and return to the
    pool; exhaustion sheds the newest stream instead of deadlocking
  * admission control — past slots+queue the PS answers 429 with
    Retry-After; bad prompts 400 before costing a slot
  * telemetry — serve histogram/gauge families pass the metrics lint
    from the live PS exposition, and serve:<model> snapshots flow
    through the health-rule pipeline into `kubeml top`
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

pytestmark = pytest.mark.serving


def _nano():
    import jax

    from kubeml_tpu.models import get_builtin
    model = get_builtin("gpt-nano")()
    module = model.module
    variables = model.init_variables(
        jax.random.PRNGKey(0),
        {"x": np.ones((1, module.max_len), np.int32)})
    return model, module, variables


def _drive(engine, limit=10_000):
    """Step the engine until every slot drains; returns finished reqs."""
    finished = []
    while engine.active():
        finished.extend(engine.step())
        limit -= 1
        assert limit > 0, "engine failed to drain"
    return finished


# ------------------------------------------------------------------ engine

def test_concurrent_decode_bit_identical_to_sequential():
    """Greedy and sampled requests produce identical tokens whether
    they share the engine with neighbours or run one at a time."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    specs = [([5, 6, 7], 6, 0.0, 0),
             ([9, 10, 11, 12], 8, 0.7, 1),
             ([3], 4, 1.3, 7)]

    def make():
        return [GenerateRequest(list(p), max_new_tokens=n, temperature=t,
                                seed=s) for p, n, t, s in specs]

    packed = DecodeEngine(module, variables, slots=4, page=4)
    reqs_packed = make()
    for r in reqs_packed:
        packed.attach(r)
    _drive(packed)

    alone = DecodeEngine(module, variables, slots=4, page=4)
    reqs_alone = make()
    for r in reqs_alone:
        alone.attach(r)
        _drive(alone)

    assert all(r.outcome == "ok" for r in reqs_packed + reqs_alone)
    assert [r.tokens for r in reqs_packed] == [r.tokens for r in reqs_alone]
    # sampled rows really sampled (different seeds diverge from greedy)
    assert reqs_packed[1].tokens != reqs_packed[0].tokens[:8]


def test_greedy_engine_matches_generate():
    """The paged decode path reproduces the model's own KV-cache
    generate() exactly for greedy decoding."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    model, module, variables = _nano()
    prompt = [5, 6, 7, 8]
    n_new = 6
    ref = model.generate(variables, np.asarray([prompt], np.int32),
                         max_new_tokens=n_new, temperature=0.0)
    engine = DecodeEngine(module, variables, slots=2, page=8)
    req = GenerateRequest(prompt, max_new_tokens=n_new)
    engine.attach(req)
    _drive(engine)
    assert req.outcome == "ok"
    assert req.tokens == ref[0, len(prompt):].tolist()


def test_join_leave_never_recompiles():
    """Membership churn — join mid-generation, cancel, EOS — is pure
    data; the engine compiles exactly TWO programs (prefill + decode),
    each once, no matter how requests churn or prompt lengths vary."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    engine = DecodeEngine(module, variables, slots=4, page=4)

    a = GenerateRequest([5, 6, 7], max_new_tokens=12)
    engine.attach(a)
    for _ in range(4):
        engine.step()
    assert engine.stats["compiles"] == 1          # decode compiled once
    assert engine.stats["prefill_compiles"] == 1  # prefill compiled once

    b = GenerateRequest([9, 10], max_new_tokens=8, temperature=0.5, seed=3)
    engine.attach(b)  # join mid-generation (different prompt length)
    for _ in range(3):
        engine.step()
    b.cancel()  # leave mid-generation
    engine.step()
    assert b.outcome == "cancelled"

    c = GenerateRequest([11], max_new_tokens=4)
    engine.attach(c)  # join after a leave
    _drive(engine)
    assert a.outcome == "ok" and c.outcome == "ok"
    assert engine.stats["compiles"] == 1
    assert engine.stats["prefill_compiles"] == 1
    assert engine.compile_tracker.compiles == 2   # two programs, total
    assert engine.compile_tracker.dispatches == \
        engine.stats["dispatches"] + engine.stats["prefill_dispatches"]


def test_pages_free_on_eos_and_return_to_pool():
    """EOS finishes the stream early, its pages free, and the pool
    drains back to zero in-use after every stream completes."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    engine = DecodeEngine(module, variables, slots=2, page=4)
    total_pages = engine.pager.free_pages

    probe = GenerateRequest([5, 6, 7], max_new_tokens=6)
    engine.attach(probe)
    _drive(engine)
    assert probe.outcome == "ok"
    assert engine.pager.in_use == 0
    assert engine.pager.free_pages == total_pages
    assert (engine._tables == 0).all()

    # same stream with eos_id = its own first token: one token, done
    eos = GenerateRequest([5, 6, 7], max_new_tokens=6,
                          eos_id=probe.tokens[0])
    engine.attach(eos)
    _drive(engine)
    assert eos.outcome == "ok"
    assert eos.tokens == probe.tokens[:1]
    assert engine.pager.in_use == 0
    assert engine.kv_utilization() == 0.0


def test_kv_exhaustion_sheds_newest_stream():
    """With every runnable slot stalled on an empty page pool, the
    NEWEST stream is shed with an error and the oldest finishes."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.pager import PageGeometry
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    # 2 usable pages of 4 tokens; each request spans 8 tokens = 2 pages
    geom = PageGeometry(slots=2, page=4, pages=3, pages_per_slot=2)
    engine = DecodeEngine(module, variables, geom=geom)
    old = GenerateRequest([5, 6, 7, 8], max_new_tokens=4)
    new = GenerateRequest([9, 10, 11, 12], max_new_tokens=4)
    engine.attach(old)
    engine.attach(new)
    _drive(engine)
    assert old.outcome == "ok" and len(old.tokens) == 4
    assert new.outcome == "error"
    assert "pages exhausted" in (new.error or "")
    assert engine.stats["stalls"] > 0
    assert engine.pager.in_use == 0  # everything returned to the pool


# ------------------------------------------------------------- PS /generate

@pytest.fixture()
def serve_ps(tmp_home):
    """A live PS with a gpt-nano checkpoint published for serving.
    Tiny slot pool (2) + queue (1) so saturation is reachable."""
    from kubeml_tpu.control.ps import ParameterServer
    from kubeml_tpu.train.checkpoint import save_checkpoint

    model, _module, variables = _nano()
    save_checkpoint("servenano", variables,
                    {"model": "gpt-nano", "function": "gpt-nano",
                     "parallelism": 1, "epoch": 0})
    ps = ParameterServer(serve_slots=2, serve_queue_depth=1)
    ps.start()
    yield ps, model, variables
    ps.stop()


def _post(url, body, timeout=60.0):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def test_generate_stream_e2e(serve_ps):
    """POST /generate streams ndjson per-token chunks, the terminal
    event carries the full token list, and the non-stream mode and the
    model's own generate() agree with it."""
    ps, model, variables = serve_ps
    prompt, n_new = [5, 6, 7, 8], 6
    ref = model.generate(variables, np.asarray([prompt], np.int32),
                         max_new_tokens=n_new, temperature=0.0)
    expected = ref[0, len(prompt):].tolist()

    resp = _post(f"{ps.url}/generate",
                 {"model_id": "servenano", "prompt": prompt,
                  "max_new_tokens": n_new})
    assert resp.headers.get("Content-Type") == "application/x-ndjson"
    events = [json.loads(line) for line in resp.read().splitlines()]
    assert [e["token"] for e in events[:-1]] == expected
    assert events[-1] == {"done": True, "tokens": expected}

    doc = json.loads(_post(f"{ps.url}/generate",
                           {"model_id": "servenano", "prompt": prompt,
                            "max_new_tokens": n_new,
                            "stream": False}).read())
    assert doc == {"tokens": expected}


def test_generate_validates_before_costing_a_slot(serve_ps):
    ps, _model, _variables = serve_ps
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(f"{ps.url}/generate",
              {"model_id": "servenano", "prompt": [0, 0]})
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(f"{ps.url}/generate", {"model_id": "servenano"})
    assert ei.value.code == 400


def test_generate_saturation_sheds_429_with_retry_after(serve_ps):
    """Slots 2 + queue 1 = capacity 3: a burst of 6 concurrent streams
    sheds the overflow with 429 + Retry-After while admitted streams
    complete normally."""
    ps, _model, _variables = serve_ps
    results = [None] * 6

    def client(i):
        try:
            resp = _post(f"{ps.url}/generate",
                         {"model_id": "servenano", "prompt": [5, 6, 7, 8],
                          "max_new_tokens": 40})
            resp.read()
            results[i] = (resp.status, None)
        except urllib.error.HTTPError as e:
            results[i] = (e.code, e.headers.get("Retry-After"))

    # serialize the first request alone so the decode service exists
    # (and its one compile lands) before the burst measures admission
    client(0)
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(1, 6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    codes = [r[0] for r in results]
    assert codes.count(200) >= 3
    shed = [r for r in results if r[0] == 429]
    assert shed, f"no request shed at capacity 3 with 6 offered: {codes}"
    assert all(int(retry) >= 1 for _, retry in shed)


def test_live_exposition_and_serve_health(serve_ps):
    """After serving traffic the PS /metrics passes the lint with the
    serve + infer-cache families present, and the serve:<model> pseudo
    job carries its snapshot through GET /health."""
    from tools.check_metrics import validate_exposition

    ps, _model, _variables = serve_ps
    _post(f"{ps.url}/generate",
          {"model_id": "servenano", "prompt": [5, 6, 7],
           "max_new_tokens": 4}).read()
    text = urllib.request.urlopen(f"{ps.url}/metrics").read().decode()
    assert validate_exposition(text) == []
    for family in ("kubeml_serve_ttft_seconds", "kubeml_serve_tpot_seconds",
                   "kubeml_serve_e2e_seconds", "kubeml_serve_active_slots",
                   "kubeml_serve_kv_page_utilization",
                   "kubeml_serve_requests_total",
                   "kubeml_serve_tokens_total",
                   "kubeml_infer_cache_entries",
                   "kubeml_infer_cache_misses_total"):
        assert f"# TYPE {family}" in text, family

    deadline = time.time() + 10
    while time.time() < deadline:
        doc = json.loads(urllib.request.urlopen(
            f"{ps.url}/health?id=serve:servenano").read())
        if doc.get("latest", {}).get("serve_slot_cap") is not None:
            break
        time.sleep(0.05)
    assert doc["state"] in ("healthy", "warning")
    latest = doc["latest"]
    assert latest["serve_slot_cap"] == 2
    assert latest["serve_queue_cap"] == 1
    assert "serve_ttft_p99" in latest
    assert latest["serve_prefill_backlog_tokens"] == 0
    assert "serve_prefix_hit_pct" in latest

    # the prefill/decode token counters publish as deltas right after
    # the request drains; poll the scrape briefly for the new families
    wanted = ("kubeml_serve_prefill_tokens_total",
              "kubeml_serve_decode_tokens_total",
              "kubeml_serve_prefill_backlog_tokens")
    deadline = time.time() + 10
    while time.time() < deadline:
        text = urllib.request.urlopen(f"{ps.url}/metrics").read().decode()
        if all(f"# TYPE {family}" in text for family in wanted):
            break
        time.sleep(0.05)
    for family in wanted:
        assert f"# TYPE {family}" in text, family
    assert validate_exposition(text) == []


# ------------------------------------------------- infer cache + batcher

def test_infer_cache_entry_cap_evicts_lru(tmp_home):
    from kubeml_tpu.control.ps import ParameterServer
    from kubeml_tpu.train.checkpoint import save_checkpoint

    _model, _module, variables = _nano()
    for i in range(3):
        save_checkpoint(f"nano{i}", variables,
                        {"model": "gpt-nano", "function": "gpt-nano",
                         "parallelism": 1, "epoch": 0})
    ps = ParameterServer(infer_cache_size=2)
    for i in range(3):
        ps._load_for_infer(f"nano{i}")
    assert list(ps._infer_cache) == ["nano1", "nano2"]
    # hit refreshes recency; metrics reflect the traffic
    ps._load_for_infer("nano1")
    assert list(ps._infer_cache) == ["nano2", "nano1"]
    text = ps.metrics.exposition()
    assert 'kubeml_infer_cache_entries{cache="checkpoints"} 2' in text
    assert 'kubeml_infer_cache_hits_total{cache="checkpoints"} 1' in text
    assert 'kubeml_infer_cache_misses_total{cache="checkpoints"} 3' in text


def test_infer_cache_yields_to_hbm_budget(tmp_home):
    """With the serving HBM budget exhausted, the cache keeps only the
    freshest entry (the request that just loaded it is using it)."""
    from kubeml_tpu.control.ps import ParameterServer
    from kubeml_tpu.train.checkpoint import save_checkpoint

    _model, _module, variables = _nano()
    for i in range(2):
        save_checkpoint(f"tiny{i}", variables,
                        {"model": "gpt-nano", "function": "gpt-nano",
                         "parallelism": 1, "epoch": 0})
    ps = ParameterServer(infer_cache_size=4, serve_hbm_budget_mb=0.0)
    ps._load_for_infer("tiny0")
    ps._load_for_infer("tiny1")
    assert list(ps._infer_cache) == ["tiny1"]


def test_infer_batcher_follower_timeout_leaves_no_dead_row():
    """A follower that times out removes its row from the pending
    bucket, so the leader's flush only serves live waiters."""
    from kubeml_tpu.api.errors import KubeMLException
    from kubeml_tpu.control.ps import InferBatcher

    b = InferBatcher(window_s=0.3, max_batch=8, timeout_s=0.05)
    key = ("m", (2,), "float32")
    b._last_arrival[key] = time.monotonic()  # force the dense window
    stacked_sizes = []

    def run(stacked):
        stacked_sizes.append(len(stacked))
        return np.zeros((len(stacked), 1))

    leader_done = threading.Event()

    def leader():
        leader_out.append(b.submit(key, np.zeros((1, 2)), run))
        leader_done.set()

    leader_out = []
    t = threading.Thread(target=leader)
    t.start()
    time.sleep(0.05)  # leader is inside its 0.3s collection window
    with pytest.raises(KubeMLException) as ei:
        b.submit(key, np.zeros((1, 2)), run)  # follower, times out
    assert "timed out" in ei.value.message
    assert leader_done.wait(5.0)
    t.join()
    # the flush saw ONLY the leader's row — the dead row left the bucket
    assert stacked_sizes == [1]
    assert len(leader_out[0]) == 1
    assert key not in b._groups


# --------------------------------------------------- health rules + top

def test_serve_health_rules_fire_on_onset():
    from kubeml_tpu.control.health import HealthEvaluator

    t = [0.0]
    ev = HealthEvaluator(clock=lambda: t[0])
    base = {"job_id": "serve:m", "serve_active_slots": 1,
            "serve_slot_cap": 2, "serve_queue_depth": 0,
            "serve_queue_cap": 2, "serve_kv_page_utilization": 0.1,
            "serve_rejected_total": 0, "serve_ttft_p50": 0.01,
            "serve_ttft_p99": 0.02}
    assert ev.observe(dict(base)) == []
    t[0] += 1.0
    fired = ev.observe(dict(base, serve_rejected_total=3))
    assert [f["rule"] for f in fired] == ["serve_saturation"]
    assert "429" in fired[0]["detail"]
    t[0] += 1.0
    # shedding stopped, but the queue sits at cap -> still saturated;
    # p99 TTFT above the 2s SLO newly fires
    fired = ev.observe(dict(base, serve_rejected_total=3,
                            serve_queue_depth=2, serve_ttft_p99=5.0))
    assert [f["rule"] for f in fired] == ["serve_ttft_slo"]
    doc = ev.verdict("serve:m")
    assert doc["state"] == "warning"
    assert {r["rule"] for r in doc["reasons"]} == {"serve_saturation",
                                                   "serve_ttft_slo"}


def test_serve_rules_ignore_training_samples():
    from kubeml_tpu.control.health import HealthEvaluator

    ev = HealthEvaluator(clock=lambda: 0.0)
    fired = ev.observe({"job_id": "job1", "train_loss": 0.4,
                        "grad_norms": [0.5], "loss_spread": 0.01})
    assert [f["rule"] for f in fired] == []
    assert "serve_queue_cap" not in ev.verdict("job1")["latest"]


# ----------------------------------- chunked prefill + prefix cache (PR 8)
#
# Bit-identity matrix for the serving-path variants registered in
# engine.SERVE_PATH_VARIANTS — every quoted name below is load-bearing:
# tools/check_serve_parity.py fails unless each variant name appears in
# a test file that also asserts exactness.

def _run_engine(module, variables, specs, **engine_kw):
    """Run request specs [(prompt, n_new, temp, seed)] through a fresh
    engine, attached together; returns the finished requests."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    engine = DecodeEngine(module, variables, **engine_kw)
    reqs = [GenerateRequest(list(p), max_new_tokens=n, temperature=t,
                            seed=s) for p, n, t, s in specs]
    for r in reqs:
        engine.attach(r)
    _drive(engine)
    return engine, reqs


def test_chunked_prefill_bit_identical_to_token_by_token():
    """'prefill_chunked' == 'prefill_token_by_token' == generate(),
    token for token, for greedy AND sampled streams — with the chunk
    size deliberately not a multiple of the page size so chunks span
    page boundaries."""
    model, module, variables = _nano()
    prompt = list(range(5, 25))              # 20 tokens, pages of 4
    specs = [(prompt, 8, 0.0, 0), (prompt[2:], 6, 0.9, 11)]
    ref = model.generate(variables, np.asarray([prompt], np.int32),
                         max_new_tokens=8, temperature=0.0)

    tbt_engine, tbt = _run_engine(module, variables, specs, slots=2,
                                  page=4, prefill_chunk=0,
                                  prefix_cache=False)
    chk_engine, chk = _run_engine(module, variables, specs, slots=2,
                                  page=4, prefill_chunk=6)
    assert all(r.outcome == "ok" for r in tbt + chk)
    for a, b in zip(tbt, chk):
        np.testing.assert_array_equal(np.asarray(a.tokens),
                                      np.asarray(b.tokens))
    np.testing.assert_array_equal(np.asarray(chk[0].tokens),
                                  ref[0, len(prompt):])
    # the chunked engine really chunked: 19+17 prefill positions at
    # C=6 is 4+3 dispatches, vs 36 token-by-token decode dispatches
    assert tbt_engine.stats["prefill_dispatches"] == 0
    assert chk_engine.stats["prefill_dispatches"] == 7
    assert chk_engine.stats["prefill_tokens"] == 36
    assert chk_engine.stats["prefill_compiles"] == 1


def test_prefix_cache_hit_and_miss_bit_identical():
    """'prefix_cache_miss' (cold) and 'prefix_cache_hit' (warm, shared
    pages) both reproduce the cache-off tokens exactly; a fully cached
    prompt costs ZERO prefill dispatches."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    prompt = list(range(30, 46))             # 16 tokens = 4 full pages
    _, ref = _run_engine(module, variables, [(prompt, 6, 0.0, 0)],
                         slots=2, page=4, prefill_chunk=0,
                         prefix_cache=False)

    engine = DecodeEngine(module, variables, slots=2, page=4,
                          prefill_chunk=4, prefix_cache=True)
    cold = GenerateRequest(prompt, max_new_tokens=6)
    engine.attach(cold)
    _drive(engine)
    assert engine.stats["prefix_hits"] == 0
    assert engine.stats["prefix_misses"] == 1
    dispatches_cold = engine.stats["prefill_dispatches"]
    assert dispatches_cold > 0

    warm = GenerateRequest(prompt, max_new_tokens=6)
    engine.attach(warm)
    _drive(engine)
    assert engine.stats["prefix_hits"] == 4          # all 4 pages shared
    assert engine.stats["prefill_dispatches"] == dispatches_cold  # zero new
    assert engine.stats["cow_splits"] >= 1   # final page split for decode

    np.testing.assert_array_equal(np.asarray(cold.tokens),
                                  np.asarray(ref[0].tokens))
    np.testing.assert_array_equal(np.asarray(warm.tokens),
                                  np.asarray(ref[0].tokens))
    # everything drains: cached pages park in the LRU, nothing leaks
    assert engine.pager.in_use == 0
    assert engine.pager.free_pages + engine.pager.evictable_pages == \
        engine.geom.usable_pages


def test_prefix_cow_split_bit_identical_under_sharing():
    """'prefix_cow_split': a stream whose decode write lands in a page
    it shares with a live neighbour gets a private copy inside the same
    dispatch — both streams produce exactly their solo tokens."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    prompt = list(range(100, 112))           # 12 tokens = 3 full pages
    solo_specs = [(prompt, 8, 0.0, 0), (prompt, 8, 1.1, 5)]
    solo = [_run_engine(module, variables, [spec], slots=2, page=4,
                        prefill_chunk=0, prefix_cache=False)[1][0]
            for spec in solo_specs]

    engine = DecodeEngine(module, variables, slots=2, page=4,
                          prefill_chunk=4, prefix_cache=True)
    first = GenerateRequest(prompt, max_new_tokens=8)
    engine.attach(first)
    # run until the prompt pages are registered (first token emitted)
    guard = 100
    while not first.tokens:
        engine.step()
        guard -= 1
        assert guard > 0
    second = GenerateRequest(prompt, max_new_tokens=8, temperature=1.1,
                             seed=5)
    engine.attach(second)   # attaches to first's pages while it decodes
    assert engine.stats["prefix_hits"] == 3
    _drive(engine)
    assert engine.stats["cow_splits"] >= 1
    np.testing.assert_array_equal(np.asarray(first.tokens),
                                  np.asarray(solo[0].tokens))
    np.testing.assert_array_equal(np.asarray(second.tokens),
                                  np.asarray(solo[1].tokens))
    assert engine.pager.in_use == 0


def test_pager_refcount_share_cow_evict_readmit_cycle():
    """Allocator state machine: register -> share -> CoW-split -> park
    in LRU -> re-admit -> evict, with the double-free guard intact."""
    from kubeml_tpu.serve.pager import (PageAllocator, PageGeometry,
                                        chain_hash)

    geom = PageGeometry(slots=2, page=4, pages=6, pages_per_slot=4)
    pager = PageAllocator(geom)
    p1 = pager.alloc()
    assert p1 == 1 and pager.writable(p1)

    digest = chain_hash(b"", [7, 8, 9, 10])
    assert pager.register_prefix(p1, digest)
    assert not pager.writable(p1)            # registered => read-only
    assert not pager.register_prefix(p1, digest)  # idempotent no-op

    # share: a second stream attaches to the cached page
    assert pager.lookup_prefix(digest) == p1
    assert pager.refcount(p1) == 2
    # CoW split: the sharer takes a private page, drops its shared ref
    dst = pager.alloc()
    pager.free([p1])
    assert pager.refcount(p1) == 1 and pager.writable(dst)

    # last ref gone: the page PARKS in the LRU, it does not free
    pager.free([p1])
    assert pager.refcount(p1) == 0
    assert pager.evictable_pages == 1
    with pytest.raises(ValueError):
        pager.free([p1])                     # double free still guarded

    # re-admit: a warm lookup revives it from the LRU
    assert pager.lookup_prefix(digest) == p1
    assert pager.refcount(p1) == 1 and pager.evictable_pages == 0
    pager.free([p1])                          # park again

    # eviction: exhaust the free list, next alloc takes the LRU page
    while pager.free_pages:
        pager.alloc()
    evicted = pager.alloc()
    assert evicted == p1 and pager.evictions == 1
    assert pager.lookup_prefix(digest) is None   # unregistered on evict
    assert pager.alloc() is None                 # now truly exhausted


def test_exhaustion_evicts_cached_pages_before_shedding():
    """A full pool with unreferenced cached pages evicts them instead
    of shedding the stream — the cache never costs capacity."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.pager import PageGeometry
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    geom = PageGeometry(slots=2, page=4, pages=3, pages_per_slot=2)
    engine = DecodeEngine(module, variables, geom=geom, prefill_chunk=4)
    first = GenerateRequest([5, 6, 7, 8], max_new_tokens=4)
    engine.attach(first)
    _drive(engine)
    assert first.outcome == "ok"
    assert engine.pager.evictable_pages == 1   # its full prompt page

    # needs both usable pages; only one is free -> must evict, not shed
    second = GenerateRequest([9, 10, 11, 12], max_new_tokens=4)
    engine.attach(second)
    _drive(engine)
    assert second.outcome == "ok" and len(second.tokens) == 4
    assert engine.pager.evictions >= 1


def test_cancel_during_prefill_restores_free_list():
    """Client cancel mid-prefill releases the partially-written pages:
    the free list returns to its pre-request size (cache off), and with
    the cache on every prefix ref is dropped too."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    engine = DecodeEngine(module, variables, slots=2, page=4,
                          prefill_chunk=2, prefix_cache=False)
    pre = engine.pager.free_pages
    req = GenerateRequest(list(range(1, 20)), max_new_tokens=4)
    engine.attach(req)
    engine.step()                      # budget C=2: one chunk, mid-prefill
    assert engine.stats["prefill_dispatches"] == 1
    assert engine._slots[0].pos < len(req.prompt) - 1   # still mid-prefill
    assert engine.pager.free_pages < pre
    req.cancel()
    engine.step()
    assert req.outcome == "cancelled"
    assert engine.pager.free_pages == pre
    assert (engine._tables == 0).all()

    # cache on: a canceled sharer drops its refs; the cached pages stay
    cached = DecodeEngine(module, variables, slots=2, page=4,
                          prefill_chunk=2, prefix_cache=True)
    warmup = GenerateRequest(list(range(1, 13)), max_new_tokens=2)
    cached.attach(warmup)
    _drive(cached)
    assert cached.pager.evictable_pages == 3
    sharer = GenerateRequest(list(range(1, 13)) + [40, 41, 42, 43],
                             max_new_tokens=2)
    cached.attach(sharer)              # takes 3 prefix refs
    assert cached.pager.in_use == 3
    cached.step()                      # mid-prefill of the tail
    sharer.cancel()
    cached.step()
    assert sharer.outcome == "cancelled"
    assert cached.pager.in_use == 0
    assert cached.pager.evictable_pages == 3
    assert cached.pager.free_pages + cached.pager.evictable_pages == \
        cached.geom.usable_pages


def test_prefill_backlog_in_retry_after_and_snapshot():
    """Saturation's Retry-After grows with the queued prompt work, and
    the snapshot carries backlog + prefix-hit% for health/top."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.service import (PREFILL_DRAIN_TOKENS_PER_S,
                                          ServeService)
    from kubeml_tpu.serve.slots import ServeSaturated

    _model, module, variables = _nano()
    engine = DecodeEngine(module, variables, slots=1, page=8)
    svc = ServeService("m", engine, max_queue=1)   # loop NOT started
    svc.submit(list(range(1, 41)), max_new_tokens=8)
    svc.submit(list(range(1, 41)), max_new_tokens=8)
    with pytest.raises(ServeSaturated) as ei:
        svc.submit(list(range(1, 41)), max_new_tokens=8)
    expect = 1.0 + (2 * 39) / PREFILL_DRAIN_TOKENS_PER_S
    assert abs(ei.value.retry_after_s - expect) < 1e-9
    snap = svc.snapshot()
    assert snap["serve_prefill_backlog_tokens"] == 2 * 39
    assert snap["serve_prefix_hit_pct"] == 0.0


def test_serve_prefill_metric_families_lint_clean():
    from kubeml_tpu.metrics.prom import MetricsRegistry
    from tools.check_metrics import validate_exposition

    m = MetricsRegistry()
    m.note_serve_prefill("m1", 32)
    m.note_serve_decode("m1", 5)
    m.note_serve_prefix_hits("m1", 3)
    m.note_serve_prefix_misses("m1", 1)
    m.set_serve_state("m1", 1, 0, 0.5, prefill_backlog=7)
    text = m.exposition()
    assert validate_exposition(text) == []
    assert 'kubeml_serve_prefill_tokens_total{model="m1"} 32' in text
    assert 'kubeml_serve_decode_tokens_total{model="m1"} 5' in text
    assert 'kubeml_serve_prefix_cache_hits_total{model="m1"} 3' in text
    assert 'kubeml_serve_prefix_cache_misses_total{model="m1"} 1' in text
    assert 'kubeml_serve_prefill_backlog_tokens{model="m1"} 7' in text
    m.clear_serve("m1")
    assert 'model="m1"' not in m.exposition()


def test_check_serve_parity_lint_passes_on_repo():
    """The lint itself, run over the real tree: every registered
    serving-path variant is covered by this file's tests."""
    import os

    from kubeml_tpu.serve.engine import SERVE_PATH_VARIANTS
    from tools.check_serve_parity import main, path_variants

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    engine_path = os.path.join(root, "kubeml_tpu", "serve", "engine.py")
    assert tuple(path_variants(engine_path)) == SERVE_PATH_VARIANTS
    assert main(["check_serve_parity.py", root]) == 0


def test_check_serve_parity_lint_selftest(tmp_path):
    """The lint catches an uncovered variant, ignores comment-only
    mentions, and fails loudly when the registry is missing."""
    from tools.check_serve_parity import main, uncovered_variants

    eng_dir = tmp_path / "kubeml_tpu" / "serve"
    eng_dir.mkdir(parents=True)
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    engine = eng_dir / "engine.py"
    engine.write_text(
        'SERVE_PATH_VARIANTS = (\n    "covered_path",\n'
        '    "naked_path",\n)\n')
    (tests_dir / "test_ok.py").write_text(
        'import numpy as np\n'
        'def test_covered():\n'
        '    # naked_path mentioned in a comment only: does not count\n'
        '    variant = "covered_path"\n'
        '    np.testing.assert_array_equal([1], [1])\n')
    assert uncovered_variants(str(engine), str(tests_dir)) == ["naked_path"]
    assert main(["lint", str(tmp_path)]) == 1
    (tests_dir / "test_fix.py").write_text(
        'import numpy as np\n'
        'def test_naked():\n'
        '    assert "naked_path"\n'
        '    np.testing.assert_array_equal([2], [2])\n')
    assert main(["lint", str(tmp_path)]) == 0
    engine.write_text("SERVE_PATH_VARIANTS = ()\n")
    assert main(["lint", str(tmp_path)]) == 1


def test_top_renders_serving_pane():
    from kubeml_tpu.cli.main import _render_top

    doc = {"id": "serve:m1", "state": "healthy", "reasons": [],
           "latest": {"serve_active_slots": 2, "serve_slot_cap": 8,
                      "serve_queue_depth": 1, "serve_queue_cap": 16,
                      "serve_kv_page_utilization": 0.25,
                      "serve_rejected_total": 3,
                      "serve_ttft_p50": 0.010, "serve_ttft_p99": 0.020}}
    out = _render_top(doc)
    assert "serve: slots 2/8" in out
    assert "queue 1/16" in out
    assert "kv pages 25%" in out
    assert "ttft p50/p99 10ms/20ms" in out
    assert "shed 3" in out
    # a training job's screen has no serving pane
    plain = _render_top({"id": "job1", "state": "healthy", "reasons": [],
                         "latest": {"train_loss": 0.5}})
    assert "serve:" not in plain
