"""Inference-plane tests (kubeml_tpu/serve/ + the PS /generate route).

The contracts pinned here are the ones the subsystem is built around:

  * bit-identity — a request generates the SAME tokens continuously
    batched with neighbours as it does running alone (slot math is
    row-independent, pages disjoint, sampling keys per (seed, pos))
  * compile pinning — joins/leaves/EOS churn slot membership as DATA;
    the decode program compiles exactly once per engine
    (JitCompileTracker), never per membership change
  * page accounting — KV pages free on EOS/cancel and return to the
    pool; exhaustion sheds the newest stream instead of deadlocking
  * admission control — past slots+queue the PS answers 429 with
    Retry-After; bad prompts 400 before costing a slot
  * telemetry — serve histogram/gauge families pass the metrics lint
    from the live PS exposition, and serve:<model> snapshots flow
    through the health-rule pipeline into `kubeml top`
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

pytestmark = pytest.mark.serving


def _nano():
    import jax

    from kubeml_tpu.models import get_builtin
    model = get_builtin("gpt-nano")()
    module = model.module
    variables = model.init_variables(
        jax.random.PRNGKey(0),
        {"x": np.ones((1, module.max_len), np.int32)})
    return model, module, variables


def _drive(engine, limit=10_000):
    """Step the engine until every slot drains; returns finished reqs."""
    finished = []
    while engine.active():
        finished.extend(engine.step())
        limit -= 1
        assert limit > 0, "engine failed to drain"
    return finished


# ------------------------------------------------------------------ engine

def test_concurrent_decode_bit_identical_to_sequential():
    """Greedy and sampled requests produce identical tokens whether
    they share the engine with neighbours or run one at a time."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    specs = [([5, 6, 7], 6, 0.0, 0),
             ([9, 10, 11, 12], 8, 0.7, 1),
             ([3], 4, 1.3, 7)]

    def make():
        return [GenerateRequest(list(p), max_new_tokens=n, temperature=t,
                                seed=s) for p, n, t, s in specs]

    packed = DecodeEngine(module, variables, slots=4, page=4)
    reqs_packed = make()
    for r in reqs_packed:
        packed.attach(r)
    _drive(packed)

    alone = DecodeEngine(module, variables, slots=4, page=4)
    reqs_alone = make()
    for r in reqs_alone:
        alone.attach(r)
        _drive(alone)

    assert all(r.outcome == "ok" for r in reqs_packed + reqs_alone)
    assert [r.tokens for r in reqs_packed] == [r.tokens for r in reqs_alone]
    # sampled rows really sampled (different seeds diverge from greedy)
    assert reqs_packed[1].tokens != reqs_packed[0].tokens[:8]


def test_greedy_engine_matches_generate():
    """The paged decode path reproduces the model's own KV-cache
    generate() exactly for greedy decoding."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    model, module, variables = _nano()
    prompt = [5, 6, 7, 8]
    n_new = 6
    ref = model.generate(variables, np.asarray([prompt], np.int32),
                         max_new_tokens=n_new, temperature=0.0)
    engine = DecodeEngine(module, variables, slots=2, page=8)
    req = GenerateRequest(prompt, max_new_tokens=n_new)
    engine.attach(req)
    _drive(engine)
    assert req.outcome == "ok"
    assert req.tokens == ref[0, len(prompt):].tolist()


def test_join_leave_never_recompiles():
    """Membership churn — join mid-generation, cancel, EOS — is pure
    data; the decode program compiles exactly once per engine."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    engine = DecodeEngine(module, variables, slots=4, page=4)

    a = GenerateRequest([5, 6, 7], max_new_tokens=12)
    engine.attach(a)
    for _ in range(4):
        engine.step()
    assert engine.stats["compiles"] == 1  # first dispatch compiled

    b = GenerateRequest([9, 10], max_new_tokens=8, temperature=0.5, seed=3)
    engine.attach(b)  # join mid-generation
    for _ in range(3):
        engine.step()
    b.cancel()  # leave mid-generation
    engine.step()
    assert b.outcome == "cancelled"

    c = GenerateRequest([11], max_new_tokens=4)
    engine.attach(c)  # join after a leave
    _drive(engine)
    assert a.outcome == "ok" and c.outcome == "ok"
    assert engine.stats["compiles"] == 1
    assert engine.compile_tracker.compiles == 1
    assert engine.compile_tracker.dispatches == engine.stats["dispatches"]


def test_pages_free_on_eos_and_return_to_pool():
    """EOS finishes the stream early, its pages free, and the pool
    drains back to zero in-use after every stream completes."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    engine = DecodeEngine(module, variables, slots=2, page=4)
    total_pages = engine.pager.free_pages

    probe = GenerateRequest([5, 6, 7], max_new_tokens=6)
    engine.attach(probe)
    _drive(engine)
    assert probe.outcome == "ok"
    assert engine.pager.in_use == 0
    assert engine.pager.free_pages == total_pages
    assert (engine._tables == 0).all()

    # same stream with eos_id = its own first token: one token, done
    eos = GenerateRequest([5, 6, 7], max_new_tokens=6,
                          eos_id=probe.tokens[0])
    engine.attach(eos)
    _drive(engine)
    assert eos.outcome == "ok"
    assert eos.tokens == probe.tokens[:1]
    assert engine.pager.in_use == 0
    assert engine.kv_utilization() == 0.0


def test_kv_exhaustion_sheds_newest_stream():
    """With every runnable slot stalled on an empty page pool, the
    NEWEST stream is shed with an error and the oldest finishes."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.pager import PageGeometry
    from kubeml_tpu.serve.slots import GenerateRequest

    _model, module, variables = _nano()
    # 2 usable pages of 4 tokens; each request spans 8 tokens = 2 pages
    geom = PageGeometry(slots=2, page=4, pages=3, pages_per_slot=2)
    engine = DecodeEngine(module, variables, geom=geom)
    old = GenerateRequest([5, 6, 7, 8], max_new_tokens=4)
    new = GenerateRequest([9, 10, 11, 12], max_new_tokens=4)
    engine.attach(old)
    engine.attach(new)
    _drive(engine)
    assert old.outcome == "ok" and len(old.tokens) == 4
    assert new.outcome == "error"
    assert "pages exhausted" in (new.error or "")
    assert engine.stats["stalls"] > 0
    assert engine.pager.in_use == 0  # everything returned to the pool


# ------------------------------------------------------------- PS /generate

@pytest.fixture()
def serve_ps(tmp_home):
    """A live PS with a gpt-nano checkpoint published for serving.
    Tiny slot pool (2) + queue (1) so saturation is reachable."""
    from kubeml_tpu.control.ps import ParameterServer
    from kubeml_tpu.train.checkpoint import save_checkpoint

    model, _module, variables = _nano()
    save_checkpoint("servenano", variables,
                    {"model": "gpt-nano", "function": "gpt-nano",
                     "parallelism": 1, "epoch": 0})
    ps = ParameterServer(serve_slots=2, serve_queue_depth=1)
    ps.start()
    yield ps, model, variables
    ps.stop()


def _post(url, body, timeout=60.0):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def test_generate_stream_e2e(serve_ps):
    """POST /generate streams ndjson per-token chunks, the terminal
    event carries the full token list, and the non-stream mode and the
    model's own generate() agree with it."""
    ps, model, variables = serve_ps
    prompt, n_new = [5, 6, 7, 8], 6
    ref = model.generate(variables, np.asarray([prompt], np.int32),
                         max_new_tokens=n_new, temperature=0.0)
    expected = ref[0, len(prompt):].tolist()

    resp = _post(f"{ps.url}/generate",
                 {"model_id": "servenano", "prompt": prompt,
                  "max_new_tokens": n_new})
    assert resp.headers.get("Content-Type") == "application/x-ndjson"
    events = [json.loads(line) for line in resp.read().splitlines()]
    assert [e["token"] for e in events[:-1]] == expected
    assert events[-1] == {"done": True, "tokens": expected}

    doc = json.loads(_post(f"{ps.url}/generate",
                           {"model_id": "servenano", "prompt": prompt,
                            "max_new_tokens": n_new,
                            "stream": False}).read())
    assert doc == {"tokens": expected}


def test_generate_validates_before_costing_a_slot(serve_ps):
    ps, _model, _variables = serve_ps
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(f"{ps.url}/generate",
              {"model_id": "servenano", "prompt": [0, 0]})
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(f"{ps.url}/generate", {"model_id": "servenano"})
    assert ei.value.code == 400


def test_generate_saturation_sheds_429_with_retry_after(serve_ps):
    """Slots 2 + queue 1 = capacity 3: a burst of 6 concurrent streams
    sheds the overflow with 429 + Retry-After while admitted streams
    complete normally."""
    ps, _model, _variables = serve_ps
    results = [None] * 6

    def client(i):
        try:
            resp = _post(f"{ps.url}/generate",
                         {"model_id": "servenano", "prompt": [5, 6, 7, 8],
                          "max_new_tokens": 40})
            resp.read()
            results[i] = (resp.status, None)
        except urllib.error.HTTPError as e:
            results[i] = (e.code, e.headers.get("Retry-After"))

    # serialize the first request alone so the decode service exists
    # (and its one compile lands) before the burst measures admission
    client(0)
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(1, 6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    codes = [r[0] for r in results]
    assert codes.count(200) >= 3
    shed = [r for r in results if r[0] == 429]
    assert shed, f"no request shed at capacity 3 with 6 offered: {codes}"
    assert all(int(retry) >= 1 for _, retry in shed)


def test_live_exposition_and_serve_health(serve_ps):
    """After serving traffic the PS /metrics passes the lint with the
    serve + infer-cache families present, and the serve:<model> pseudo
    job carries its snapshot through GET /health."""
    from tools.check_metrics import validate_exposition

    ps, _model, _variables = serve_ps
    _post(f"{ps.url}/generate",
          {"model_id": "servenano", "prompt": [5, 6, 7],
           "max_new_tokens": 4}).read()
    text = urllib.request.urlopen(f"{ps.url}/metrics").read().decode()
    assert validate_exposition(text) == []
    for family in ("kubeml_serve_ttft_seconds", "kubeml_serve_tpot_seconds",
                   "kubeml_serve_e2e_seconds", "kubeml_serve_active_slots",
                   "kubeml_serve_kv_page_utilization",
                   "kubeml_serve_requests_total",
                   "kubeml_serve_tokens_total",
                   "kubeml_infer_cache_entries",
                   "kubeml_infer_cache_misses_total"):
        assert f"# TYPE {family}" in text, family

    deadline = time.time() + 10
    while time.time() < deadline:
        doc = json.loads(urllib.request.urlopen(
            f"{ps.url}/health?id=serve:servenano").read())
        if doc.get("latest", {}).get("serve_slot_cap") is not None:
            break
        time.sleep(0.05)
    assert doc["state"] in ("healthy", "warning")
    latest = doc["latest"]
    assert latest["serve_slot_cap"] == 2
    assert latest["serve_queue_cap"] == 1
    assert "serve_ttft_p99" in latest


# ------------------------------------------------- infer cache + batcher

def test_infer_cache_entry_cap_evicts_lru(tmp_home):
    from kubeml_tpu.control.ps import ParameterServer
    from kubeml_tpu.train.checkpoint import save_checkpoint

    _model, _module, variables = _nano()
    for i in range(3):
        save_checkpoint(f"nano{i}", variables,
                        {"model": "gpt-nano", "function": "gpt-nano",
                         "parallelism": 1, "epoch": 0})
    ps = ParameterServer(infer_cache_size=2)
    for i in range(3):
        ps._load_for_infer(f"nano{i}")
    assert list(ps._infer_cache) == ["nano1", "nano2"]
    # hit refreshes recency; metrics reflect the traffic
    ps._load_for_infer("nano1")
    assert list(ps._infer_cache) == ["nano2", "nano1"]
    text = ps.metrics.exposition()
    assert 'kubeml_infer_cache_entries{cache="checkpoints"} 2' in text
    assert 'kubeml_infer_cache_hits_total{cache="checkpoints"} 1' in text
    assert 'kubeml_infer_cache_misses_total{cache="checkpoints"} 3' in text


def test_infer_cache_yields_to_hbm_budget(tmp_home):
    """With the serving HBM budget exhausted, the cache keeps only the
    freshest entry (the request that just loaded it is using it)."""
    from kubeml_tpu.control.ps import ParameterServer
    from kubeml_tpu.train.checkpoint import save_checkpoint

    _model, _module, variables = _nano()
    for i in range(2):
        save_checkpoint(f"tiny{i}", variables,
                        {"model": "gpt-nano", "function": "gpt-nano",
                         "parallelism": 1, "epoch": 0})
    ps = ParameterServer(infer_cache_size=4, serve_hbm_budget_mb=0.0)
    ps._load_for_infer("tiny0")
    ps._load_for_infer("tiny1")
    assert list(ps._infer_cache) == ["tiny1"]


def test_infer_batcher_follower_timeout_leaves_no_dead_row():
    """A follower that times out removes its row from the pending
    bucket, so the leader's flush only serves live waiters."""
    from kubeml_tpu.api.errors import KubeMLException
    from kubeml_tpu.control.ps import InferBatcher

    b = InferBatcher(window_s=0.3, max_batch=8, timeout_s=0.05)
    key = ("m", (2,), "float32")
    b._last_arrival[key] = time.monotonic()  # force the dense window
    stacked_sizes = []

    def run(stacked):
        stacked_sizes.append(len(stacked))
        return np.zeros((len(stacked), 1))

    leader_done = threading.Event()

    def leader():
        leader_out.append(b.submit(key, np.zeros((1, 2)), run))
        leader_done.set()

    leader_out = []
    t = threading.Thread(target=leader)
    t.start()
    time.sleep(0.05)  # leader is inside its 0.3s collection window
    with pytest.raises(KubeMLException) as ei:
        b.submit(key, np.zeros((1, 2)), run)  # follower, times out
    assert "timed out" in ei.value.message
    assert leader_done.wait(5.0)
    t.join()
    # the flush saw ONLY the leader's row — the dead row left the bucket
    assert stacked_sizes == [1]
    assert len(leader_out[0]) == 1
    assert key not in b._groups


# --------------------------------------------------- health rules + top

def test_serve_health_rules_fire_on_onset():
    from kubeml_tpu.control.health import HealthEvaluator

    t = [0.0]
    ev = HealthEvaluator(clock=lambda: t[0])
    base = {"job_id": "serve:m", "serve_active_slots": 1,
            "serve_slot_cap": 2, "serve_queue_depth": 0,
            "serve_queue_cap": 2, "serve_kv_page_utilization": 0.1,
            "serve_rejected_total": 0, "serve_ttft_p50": 0.01,
            "serve_ttft_p99": 0.02}
    assert ev.observe(dict(base)) == []
    t[0] += 1.0
    fired = ev.observe(dict(base, serve_rejected_total=3))
    assert [f["rule"] for f in fired] == ["serve_saturation"]
    assert "429" in fired[0]["detail"]
    t[0] += 1.0
    # shedding stopped, but the queue sits at cap -> still saturated;
    # p99 TTFT above the 2s SLO newly fires
    fired = ev.observe(dict(base, serve_rejected_total=3,
                            serve_queue_depth=2, serve_ttft_p99=5.0))
    assert [f["rule"] for f in fired] == ["serve_ttft_slo"]
    doc = ev.verdict("serve:m")
    assert doc["state"] == "warning"
    assert {r["rule"] for r in doc["reasons"]} == {"serve_saturation",
                                                   "serve_ttft_slo"}


def test_serve_rules_ignore_training_samples():
    from kubeml_tpu.control.health import HealthEvaluator

    ev = HealthEvaluator(clock=lambda: 0.0)
    fired = ev.observe({"job_id": "job1", "train_loss": 0.4,
                        "grad_norms": [0.5], "loss_spread": 0.01})
    assert [f["rule"] for f in fired] == []
    assert "serve_queue_cap" not in ev.verdict("job1")["latest"]


def test_top_renders_serving_pane():
    from kubeml_tpu.cli.main import _render_top

    doc = {"id": "serve:m1", "state": "healthy", "reasons": [],
           "latest": {"serve_active_slots": 2, "serve_slot_cap": 8,
                      "serve_queue_depth": 1, "serve_queue_cap": 16,
                      "serve_kv_page_utilization": 0.25,
                      "serve_rejected_total": 3,
                      "serve_ttft_p50": 0.010, "serve_ttft_p99": 0.020}}
    out = _render_top(doc)
    assert "serve: slots 2/8" in out
    assert "queue 1/16" in out
    assert "kv pages 25%" in out
    assert "ttft p50/p99 10ms/20ms" in out
    assert "shed 3" in out
    # a training job's screen has no serving pane
    plain = _render_top({"id": "job1", "state": "healthy", "reasons": [],
                         "latest": {"train_loss": 0.5}})
    assert "serve:" not in plain
