"""SyncDPEngine: per-step gradient averaging + ZeRO-1 state sharding.

Runs on the 8-virtual-CPU-device mesh (conftest).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from kubeml_tpu.models import get_builtin
from kubeml_tpu.parallel.mesh import DATA_AXIS
from kubeml_tpu.parallel.syncdp import SyncDPEngine

S, B = 4, 32  # steps per dispatch, global batch


def _problem(seed=0, n_features=16, ncls=4):
    model = get_builtin("mlp")(hidden=32, num_classes=ncls)
    rng = np.random.RandomState(seed)
    centers = rng.randn(ncls, n_features) * 3
    y = rng.randint(0, ncls, size=(S * 6, B)).astype(np.int32)
    x = (centers[y] + rng.randn(*y.shape, n_features)).astype(np.float32)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x[0])})
    return model, x, y, variables


def _single_stream(model, variables, x, y, rngs, tx, steps):
    """Reference: plain sequential training on the full global batch."""
    params = variables["params"]
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, mb, rng):
        def scalar_loss(p):
            per_ex, _ = model.loss({"params": p}, mb,
                                   jax.random.wrap_key_data(rng),
                                   jnp.ones(mb["y"].shape[0]))
            return per_ex.mean()

        grads = jax.grad(scalar_loss)(params)
        updates, opt_state2 = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state2

    for i in range(steps):
        params, opt_state = step(
            params, opt_state,
            {"x": jnp.asarray(x[i]), "y": jnp.asarray(y[i])},
            jnp.asarray(rngs[i]))
    return params


@pytest.mark.parametrize("zero1", [True, False])
def test_syncdp_matches_single_stream(mesh8, zero1):
    """Sharded-batch + (optionally) sharded-opt-state training equals the
    same adam steps run sequentially on one stream — GSPMD's inserted
    collectives change nothing numerically (f32 model)."""
    model, x, y, variables = _problem()
    rngs = np.random.RandomState(1).randint(
        0, 2**31, size=(S * 2, 2)).astype(np.uint32)

    tx = optax.adam(1e-2)
    ref_params = _single_stream(model, variables, x, y, rngs, tx, S * 2)

    eng = SyncDPEngine(mesh8, model.loss, lambda lr, epoch: optax.adam(1e-2),
                       zero1=zero1, donate=False)
    state = eng.init_state(variables)
    for r in range(2):
        sl = slice(r * S, (r + 1) * S)
        state, losses = eng.train_steps(
            state, {"x": jnp.asarray(x[sl]), "y": jnp.asarray(y[sl])},
            np.ones((S, B), np.float32), rngs[sl], lr=0.0, epoch=0)
        assert losses.shape == (S,)
    for pr, pe in zip(jax.tree_util.tree_leaves(ref_params),
                      jax.tree_util.tree_leaves(state["params"])):
        np.testing.assert_allclose(np.asarray(pe), np.asarray(pr),
                                   rtol=1e-4, atol=1e-5)


def test_zero1_opt_state_is_sharded(mesh8):
    """Adam's m/v for divisible-dim-0 leaves land sharded over `data`
    (each device stores 1/8), and zero1=False keeps them replicated."""
    model, x, y, variables = _problem()

    for zero1, want in ((True, P(DATA_AXIS)), (False, P())):
        eng = SyncDPEngine(mesh8, model.loss,
                           lambda lr, epoch: optax.adam(1e-2),
                           zero1=zero1, donate=False)
        state = eng.init_state(variables)
        mu = state["opt_state"][0].mu  # adam first moment, mirrors params
        # find a leaf with divisible dim 0 (16 or 32 features, lanes=8)
        leaves = [l for l in jax.tree_util.tree_leaves(mu)
                  if l.ndim >= 1 and l.shape[0] % 8 == 0]
        assert leaves, "test problem must have a divisible leaf"
        assert all(l.sharding.spec == want for l in leaves), zero1
        if zero1:
            shard = leaves[0].addressable_shards[0].data
            assert shard.shape[0] == leaves[0].shape[0] // 8

        # the layout must survive a training dispatch (the scan carry)
        state, _ = eng.train_steps(
            state, {"x": jnp.asarray(x[:S]), "y": jnp.asarray(y[:S])},
            np.ones((S, B), np.float32),
            np.zeros((S, 2), np.uint32), lr=0.0, epoch=0)
        mu2 = state["opt_state"][0].mu
        leaves2 = [l for l in jax.tree_util.tree_leaves(mu2)
                   if l.ndim >= 1 and l.shape[0] % 8 == 0]
        assert all(l.sharding.spec == want for l in leaves2), zero1


def test_fsdp_matches_single_stream(mesh8):
    """ZeRO-3: params sharded over `data` (1/8 per chip), training still
    bit-matches the sequential run — FSDP is only a layout choice."""
    model, x, y, variables = _problem()
    rngs = np.random.RandomState(1).randint(
        0, 2**31, size=(S * 2, 2)).astype(np.uint32)
    tx = optax.adam(1e-2)
    ref_params = _single_stream(model, variables, x, y, rngs, tx, S * 2)

    eng = SyncDPEngine(mesh8, model.loss, lambda lr, epoch: optax.adam(1e-2),
                       fsdp=True, donate=False)
    state = eng.init_state(variables)
    # params are REALLY sharded: a divisible leaf stores 1/8 per device
    leaves = [l for l in jax.tree_util.tree_leaves(state["params"])
              if l.ndim >= 1 and l.shape[0] % 8 == 0]
    assert leaves and all(l.sharding.spec == P(DATA_AXIS) for l in leaves)
    assert leaves[0].addressable_shards[0].data.shape[0] == \
        leaves[0].shape[0] // 8

    for r in range(2):
        sl = slice(r * S, (r + 1) * S)
        state, _ = eng.train_steps(
            state, {"x": jnp.asarray(x[sl]), "y": jnp.asarray(y[sl])},
            np.ones((S, B), np.float32), rngs[sl], lr=0.0, epoch=0)
    # the FSDP layout survived both dispatches
    leaves2 = [l for l in jax.tree_util.tree_leaves(state["params"])
               if l.ndim >= 1 and l.shape[0] % 8 == 0]
    assert all(l.sharding.spec == P(DATA_AXIS) for l in leaves2)
    for pr, pe in zip(jax.tree_util.tree_leaves(ref_params),
                      jax.tree_util.tree_leaves(state["params"])):
        np.testing.assert_allclose(np.asarray(pe), np.asarray(pr),
                                   rtol=1e-4, atol=1e-5)


def test_syncdp_padded_samples_do_not_contribute(mesh8):
    """A zero sample_mask entry must leave the update identical to the
    batch without that example (masked-mean grads)."""
    model, x, y, variables = _problem(seed=2)
    eng = SyncDPEngine(mesh8, model.loss, lambda lr, epoch: optax.sgd(0.1),
                       zero1=False, donate=False)
    rngs = np.zeros((1, 2), np.uint32)

    # batch A: B real examples; batch B: same but last 8 are garbage + masked
    xa, ya = x[:1], y[:1]
    xb = xa.copy()
    xb[0, B - 8:] = 1e3  # poison the padded slots
    mask = np.ones((1, B), np.float32)
    mask[0, B - 8:] = 0.0

    sa = eng.init_state(variables)
    sa, _ = eng.train_steps(sa, {"x": jnp.asarray(xa[:, :B - 8]),
                                 "y": jnp.asarray(ya[:, :B - 8])},
                            np.ones((1, B - 8), np.float32), rngs,
                            lr=0.0, epoch=0)
    sb = eng.init_state(variables)
    sb, _ = eng.train_steps(sb, {"x": jnp.asarray(xb), "y": jnp.asarray(ya)},
                            mask, rngs, lr=0.0, epoch=0)
    for pa, pb in zip(jax.tree_util.tree_leaves(sa["params"]),
                      jax.tree_util.tree_leaves(sb["params"])):
        np.testing.assert_allclose(np.asarray(pb), np.asarray(pa),
                                   rtol=1e-5, atol=1e-6)


def test_syncdp_converges_and_state_persists(mesh8):
    """Loss falls across dispatches WITHOUT optimizer reset — the defining
    difference from the K-avg engine (which re-inits opt state per round;
    adam's momentum here must carry across train_steps calls)."""
    model, x, y, variables = _problem(seed=3)
    eng = SyncDPEngine(mesh8, model.loss,
                       lambda lr, epoch: optax.adam(1e-2), donate=False)
    state = eng.init_state(variables)
    rng = np.random.RandomState(0)
    first = last = None
    for r in range(6):
        sl = slice(r * S, (r + 1) * S)
        state, losses = eng.train_steps(
            state, {"x": jnp.asarray(x[sl]), "y": jnp.asarray(y[sl])},
            np.ones((S, B), np.float32),
            rng.randint(0, 2**31, size=(S, 2)).astype(np.uint32),
            lr=0.0, epoch=0)
        mean = float(np.asarray(losses).mean())
        first = mean if first is None else first
        last = mean
    assert last < first * 0.5, (first, last)
    # adam's step count advanced across all dispatches (no reset)
    counts = [l for l in jax.tree_util.tree_leaves(state["opt_state"])
              if getattr(l, "ndim", None) == 0]
    assert any(int(c) == 6 * S for c in counts), counts
