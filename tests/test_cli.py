"""CLI tests against a live in-process deployment."""

import json
import os

import numpy as np
import pytest

from kubeml_tpu.cli.main import main
from kubeml_tpu.control.deployment import start_deployment


@pytest.fixture()
def stack(tmp_path, tmp_home, mesh8, monkeypatch):
    dep = start_deployment(mesh=mesh8)
    monkeypatch.setenv("KUBEML_CONTROLLER_URL", dep.controller_url)
    rng = np.random.RandomState(0)
    y = rng.randint(0, 3, 600).astype(np.int32)
    x = rng.randn(600, 8).astype(np.float32) * 1.5
    x[np.arange(600), y * 2] += 3.0
    paths = {}
    for name, arr in (("xtr", x), ("ytr", y), ("xte", x[:100]),
                      ("yte", y[:100])):
        p = tmp_path / f"{name}.npy"
        np.save(p, arr)
        paths[name] = str(p)
    yield dep, paths, tmp_path
    dep.stop()


def run_cli(dep, *argv):
    return main(["--controller", dep.controller_url] + list(argv))


def test_cli_full_flow(stack, capsys):
    dep, paths, tmp_path = stack
    run_cli(dep, "dataset", "create", "-n", "blobs",
            "--traindata", paths["xtr"], "--trainlabels", paths["ytr"],
            "--testdata", paths["xte"], "--testlabels", paths["yte"])
    assert "created dataset blobs" in capsys.readouterr().out

    run_cli(dep, "dataset", "list")
    assert "blobs" in capsys.readouterr().out

    run_cli(dep, "fn", "list")
    assert "mlp" in capsys.readouterr().out

    run_cli(dep, "train", "-f", "mlp", "-d", "blobs", "-e", "2", "-b", "32",
            "--lr", "0.1", "-p", "2", "--static")
    job_id = capsys.readouterr().out.strip()
    assert len(job_id) == 8

    # job start is async through the scheduler queue: wait for the history
    import time
    from kubeml_tpu.train.history import HistoryStore
    deadline = time.time() + 120
    while time.time() < deadline:
        if any(h.id == job_id for h in HistoryStore().list()):
            break
        time.sleep(0.3)

    run_cli(dep, "history", "list")
    assert job_id in capsys.readouterr().out

    run_cli(dep, "history", "get", "--id", job_id)
    h = json.loads(capsys.readouterr().out)
    assert len(h["data"]["train_loss"]) == 2

    # infer from a datafile
    df = tmp_path / "in.npy"
    np.save(df, np.zeros((3, 8), np.float32))
    run_cli(dep, "infer", "-n", job_id, "--datafile", str(df))
    preds = json.loads(capsys.readouterr().out)
    assert len(preds) == 3

    # logs exist and mention the epochs
    run_cli(dep, "logs", "--id", job_id)
    out = capsys.readouterr().out
    assert "epoch 1/2" in out and "epoch 2/2" in out

    run_cli(dep, "history", "delete", "--id", job_id)
    capsys.readouterr()
    run_cli(dep, "task", "prune")
    assert "pruned 1 orphaned" in capsys.readouterr().out


def test_cli_validation_errors(stack, capsys):
    dep, paths, _ = stack
    with pytest.raises(SystemExit):
        run_cli(dep, "train", "-f", "mlp", "-d", "nope", "-e", "1",
                "--lr", "0.1")
    assert "nope" in capsys.readouterr().err
    with pytest.raises(SystemExit):
        run_cli(dep, "train", "-f", "nope", "-d", "blobs", "-e", "1",
                "--lr", "0.1")
    with pytest.raises(SystemExit):
        run_cli(dep, "train", "-f", "mlp", "-d", "blobs", "-e", "1",
                "-b", "4096", "--lr", "0.1")
    err = capsys.readouterr().err
    assert "batch" in err


def test_cli_parallelism_flags(stack, capsys):
    """--tensor-parallel/--seq-parallel/--seq-impl parse, validate, and
    land in the wire request."""
    from kubeml_tpu.cli.main import build_parser
    p = build_parser()
    args = p.parse_args(["train", "-f", "bert-tiny", "-d", "toks", "-e",
                         "1", "--lr", "0.001", "--tensor-parallel", "2"])
    assert args.tensor_parallel == 2 and args.seq_parallel == 1
    args = p.parse_args(["train", "-f", "gpt-mini", "-d", "toks", "-e",
                         "1", "--lr", "0.001", "--seq-parallel", "4",
                         "--seq-impl", "ulysses"])
    assert args.seq_parallel == 4 and args.seq_impl == "ulysses"

    dep, paths, _ = stack
    with pytest.raises(SystemExit):
        run_cli(dep, "train", "-f", "mlp", "-d", "blobs", "-e", "1",
                "--lr", "0.1", "--tensor-parallel", "0")
    assert ">= 1" in capsys.readouterr().err
    # TP+SP combined is ACCEPTED since round 3 (manual path); only the
    # ulysses impl is rejected (it re-shards the head axis TP owns)
    with pytest.raises(SystemExit):
        run_cli(dep, "train", "-f", "mlp", "-d", "blobs", "-e", "1",
                "--lr", "0.1", "--tensor-parallel", "2",
                "--seq-parallel", "2", "--seq-impl", "ulysses")
    assert "ring" in capsys.readouterr().err
    # wire round-trip
    from kubeml_tpu.api.types import TrainOptions
    opts = TrainOptions(n_model=2, n_seq=1, seq_impl="ulysses",
                        tp_impl="manual")
    assert TrainOptions.from_dict(opts.to_dict()).n_model == 2
    assert TrainOptions.from_dict(opts.to_dict()).seq_impl == "ulysses"
    assert TrainOptions.from_dict(opts.to_dict()).tp_impl == "manual"


def test_serve_role_flags_parse():
    from kubeml_tpu.cli.main import build_parser
    p = build_parser()
    args = p.parse_args(["serve", "--role", "ps", "--port", "9999",
                         "--scheduler-url", "http://h:1",
                         "--standalone-jobs"])
    assert args.role == "ps" and args.port == 9999
    assert args.scheduler_url == "http://h:1" and args.standalone_jobs
    args = p.parse_args(["serve"])
    assert args.role == "all" and not args.standalone_jobs


def test_env_spec_parser():
    """';' separates pairs so VALUES may carry commas (device lists) —
    the --job-partition grammar."""
    from kubeml_tpu.utils.env import parse_env_spec
    assert parse_env_spec("TPU_VISIBLE_DEVICES=0,1") == {
        "TPU_VISIBLE_DEVICES": "0,1"}
    assert parse_env_spec("A=1;B=x,y; C=z") == {
        "A": "1", "B": "x,y", "C": "z"}
    with pytest.raises(ValueError, match="KEY=VALUE"):
        parse_env_spec("NOVALUE")


def test_serve_job_partition_flag_parses():
    from kubeml_tpu.cli.main import build_parser
    p = build_parser()
    args = p.parse_args(["serve", "--standalone-jobs",
                         "--job-partition", "TPU_VISIBLE_DEVICES=0,1",
                         "--job-partition",
                         "TPU_VISIBLE_DEVICES=2,3;FOO=bar"])
    assert args.job_partition == ["TPU_VISIBLE_DEVICES=0,1",
                                  "TPU_VISIBLE_DEVICES=2,3;FOO=bar"]
    from kubeml_tpu.utils.env import parse_env_spec
    parts = [parse_env_spec(s) for s in args.job_partition]
    assert parts[1] == {"TPU_VISIBLE_DEVICES": "2,3", "FOO": "bar"}
