"""Fault-tolerant sync rounds: on-device non-finite guard, worker
quarantine/abort policy, and the deterministic FaultPlan harness.

Every test here is coordinate-driven (kubeml_tpu/faults.py): injections
fire at named (epoch, round, worker) coordinates, never from wall-clock
or unseeded randomness — tools/check_fault_tests.py lints this file for
violations, and test_fault_test_lint below keeps the lint itself in the
tier.
"""

import dataclasses
import json
import os
import threading
import time
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubeml_tpu.api.errors import KubeMLException
from kubeml_tpu.api.types import TrainOptions, TrainRequest
from kubeml_tpu.data.loader import RoundBatch
from kubeml_tpu.data.registry import DatasetRegistry
from kubeml_tpu.faults import FaultPlan
from kubeml_tpu.models import get_builtin
from kubeml_tpu.parallel.kavg import KAvgEngine
from kubeml_tpu.parallel.syncdp import SyncDPEngine
from kubeml_tpu.train.checkpoint import load_checkpoint, save_checkpoint
from kubeml_tpu.train.history import HistoryStore
from kubeml_tpu.train.job import TrainJob

from tests.test_job import ToyDataset, make_blobs, make_task
from tests.test_kavg import (D, linear_loss, linear_metrics,
                             numpy_reference, sgd_factory)
from tests.test_syncdp import B as SYNC_B
from tests.test_syncdp import S as SYNC_S
from tests.test_syncdp import _problem

pytestmark = pytest.mark.faults


# ----------------------------------------------------------- plan parsing


def test_fault_plan_parsing():
    plan = FaultPlan.parse(
        '{"events": [{"kind": "nan", "epoch": 1, "round": 2, "worker": 3}]}')
    ev = plan.events[0]
    assert (ev.kind, ev.epoch, ev.round, ev.worker) == ("nan", 1, 2, 3)
    assert ev.matches(1, 2) and not ev.matches(1, 3) and not ev.matches(0, 2)

    # bare list parses too; unset coordinates default to wildcards
    plan = FaultPlan.parse([{"kind": "dropout"}])
    ev = plan.events[0]
    assert (ev.epoch, ev.round, ev.worker) == (-1, -1, -1)
    assert ev.matches(0, 5) and ev.matches(7, 0)
    assert plan.has("dropout") and not plan.has("crash")

    # already-parsed plans pass through
    assert FaultPlan.parse(plan) is plan

    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.parse([{"kind": "explode"}])
    with pytest.raises(ValueError, match="must be a list"):
        FaultPlan.parse('{"events": 3}')


def _round_batch(rnd, W=4, S=2, Bz=4):
    rs = np.random.RandomState(3)
    return RoundBatch(
        batch={"x": rs.randn(W, S, Bz, D).astype(np.float32),
               "y": rs.randn(W, S, Bz).astype(np.float32)},
        sample_mask=np.ones((W, S, Bz), np.float32),
        step_mask=np.ones((W, S), np.float32),
        worker_mask=np.ones(W, np.float32),
        rngs=np.zeros((W, S, 2), np.uint32),
        round_index=rnd, num_rounds=4)


def test_fault_plan_dropout_slow_and_coordinates():
    plan = FaultPlan.parse([
        {"kind": "dropout", "epoch": 0, "round": 1, "worker": 2},
        {"kind": "slow", "epoch": 0, "round": 0, "duration_s": 0.01},
    ])
    out0 = plan(_round_batch(0))
    assert out0.worker_mask.sum() == 4  # dropout targets round 1 only
    assert plan.injected["slow"] == 1 and plan.injected["dropout"] == 0

    rb1 = _round_batch(1)
    out1 = plan(rb1)
    assert out1.worker_mask[2] == 0.0 and out1.worker_mask.sum() == 3
    assert rb1.worker_mask.sum() == 4  # the original mask is never edited
    assert plan.injected["dropout"] == 1

    plan.epoch = 1  # wrong epoch: nothing fires
    assert plan(_round_batch(1)).worker_mask.sum() == 4
    assert plan.injected["dropout"] == 1


def test_fault_plan_nan_injection_targets_one_worker():
    plan = FaultPlan.parse([{"kind": "nan", "round": 0, "worker": 1}])
    rb = _round_batch(0)
    out = plan.inject_batch(rb)
    assert np.isnan(out.batch["x"][1]).all()
    assert np.isnan(out.batch["y"][1]).all()
    assert np.isfinite(out.batch["x"][0]).all()
    assert np.isfinite(rb.batch["x"][1]).all()  # copy-on-poison
    assert plan.injected["nan"] == 1
    # non-matching round passes the batch through untouched
    rb3 = _round_batch(3)
    assert plan.inject_batch(rb3) is rb3


# ------------------------------------------------- engine merge guard


def test_engine_drops_nonfinite_worker_bit_identical(mesh8):
    """A worker whose local steps go non-finite merges EXACTLY as if its
    mask bit had been 0: same psum sequence, bit-identical weights."""
    W, S, Bz, lr = 8, 3, 4, 0.05
    rs = np.random.RandomState(11)
    xs = rs.randn(W, S, Bz, D).astype(np.float32)
    ys = rs.randn(W, S, Bz).astype(np.float32)
    w0 = rs.randn(D).astype(np.float32)
    poisoned = xs.copy()
    poisoned[1] = np.nan

    engine = KAvgEngine(mesh8, linear_loss, linear_metrics, sgd_factory,
                        donate=False)
    variables = {"params": {"w": jnp.asarray(w0)}}
    kw = dict(sample_mask=np.ones((W, S, Bz)), step_mask=np.ones((W, S)),
              rngs=np.zeros((W, S, 2), np.uint32), lr=lr, epoch=0)

    avg, stats = engine.train_round(
        variables, {"x": jnp.asarray(poisoned), "y": jnp.asarray(ys)},
        worker_mask=np.ones(W), **kw)
    dropped = np.asarray(stats.dropped)
    assert dropped.sum() == 1 and dropped[1] == 1
    assert stats.contributors == W - 1
    assert float(stats.loss_sum[1]) == 0.0  # its loss never merges either

    # the same round with worker 1 pre-masked out by the host
    mask = np.ones(W)
    mask[1] = 0.0
    avg2, stats2 = engine.train_round(
        variables, {"x": jnp.asarray(poisoned), "y": jnp.asarray(ys)},
        worker_mask=mask, **kw)
    assert stats2.contributors == W - 1
    np.testing.assert_array_equal(np.asarray(avg["params"]["w"]),
                                  np.asarray(avg2["params"]["w"]))
    # and both match the numpy reference over the 7 finite workers
    expect = numpy_reference(w0, xs, ys, lr, mask, [S] * W)
    np.testing.assert_allclose(np.asarray(avg["params"]["w"]), expect,
                               rtol=1e-5, atol=1e-6)


def test_all_nonfinite_round_carries_params_forward(mesh8):
    """Every contributor dropped: the round is a no-op (round-start
    weights carried forward bit-identically), never a silent zeroing."""
    W, S, Bz = 8, 2, 4
    rs = np.random.RandomState(12)
    xs = np.full((W, S, Bz, D), np.nan, np.float32)
    ys = rs.randn(W, S, Bz).astype(np.float32)
    w0 = rs.randn(D).astype(np.float32)
    engine = KAvgEngine(mesh8, linear_loss, linear_metrics, sgd_factory,
                        donate=False)
    avg, stats = engine.train_round(
        {"params": {"w": jnp.asarray(w0)}},
        {"x": jnp.asarray(xs), "y": jnp.asarray(ys)},
        sample_mask=np.ones((W, S, Bz)), step_mask=np.ones((W, S)),
        worker_mask=np.ones(W), rngs=np.zeros((W, S, 2), np.uint32),
        lr=0.05, epoch=0)
    assert np.asarray(stats.dropped).sum() == W
    assert stats.contributors == 0
    np.testing.assert_array_equal(np.asarray(avg["params"]["w"]), w0)


def test_syncdp_skips_nonfinite_step(mesh8):
    """A poisoned step under syncdp skips the optimizer update: params
    end bit-identical to the same dispatch with that step masked out,
    and the skip is flagged in last_skipped_device."""
    model, x, y, variables = _problem()
    rngs = np.random.RandomState(2).randint(
        0, 2**31, size=(SYNC_S, 2)).astype(np.uint32)
    x_bad = x[:SYNC_S].copy()
    x_bad[2] = np.nan
    smask = np.ones((SYNC_S, SYNC_B), np.float32)

    def run(xarr, sm):
        eng = SyncDPEngine(mesh8, model.loss,
                           lambda lr, epoch: optax.sgd(0.05), donate=False)
        state = eng.init_state(variables)
        state, losses = eng.train_steps(
            state, {"x": jnp.asarray(xarr), "y": jnp.asarray(y[:SYNC_S])},
            sm, rngs, lr=0.05, epoch=0)
        return eng, state, losses

    eng_a, st_a, losses_a = run(x_bad, smask)
    skipped = np.asarray(eng_a.last_skipped_device)
    np.testing.assert_array_equal(skipped, [0.0, 0.0, 1.0, 0.0])
    assert float(losses_a[2]) == 0.0

    smask_b = smask.copy()
    smask_b[2] = 0.0
    _, st_b, _ = run(x[:SYNC_S], smask_b)
    for a, b in zip(jax.tree_util.tree_leaves(st_a["params"]),
                    jax.tree_util.tree_leaves(st_b["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ----------------------------------------------------- job-level policy


@pytest.fixture()
def jobenv(tmp_path, tmp_home, mesh8):
    reg = DatasetRegistry()
    make_blobs(reg)
    return reg, HistoryStore(), mesh8


def _run_faulted(jobenv, job_id, plan, *, epochs=2, parallelism=4,
                 engine="kavg", expect_raise=None, **optkw):
    reg, store, mesh = jobenv
    task = make_task(job_id=job_id, epochs=epochs, parallelism=parallelism,
                     engine=engine)
    opts = task.parameters.options
    if plan is not None:
        opts.fault_plan = plan if isinstance(plan, str) else json.dumps(plan)
    # pin both arms of every comparison to host staging: the nan events
    # disable the device cache on their own arm, so the clean arm must
    # not silently take the index-fed path instead
    opts.device_cache = "off"
    for k, v in optkw.items():
        setattr(opts, k, v)
    job = TrainJob(task, get_builtin("mlp")(hidden=16, num_classes=4),
                   ToyDataset(), mesh, registry=reg, history_store=store)
    if expect_raise is not None:
        with pytest.raises(KubeMLException, match=expect_raise) as ei:
            job.train()
        return job, ei
    return job, job.train()


def test_job_nan_drop_matches_premasked_run(jobenv):
    """End-to-end acceptance: one worker emits NaN mid-epoch; the job
    completes, the drop lands in history, and the final weights are
    bit-identical to the run whose mask excluded that worker from the
    start (same coordinates, dropout instead of nan)."""
    coords = {"epoch": 0, "round": 0, "worker": 1}
    job_a, rec_a = _run_faulted(jobenv, "fnan1",
                                [dict(coords, kind="nan")])
    job_b, rec_b = _run_faulted(jobenv, "fdrop1",
                                [dict(coords, kind="dropout")])
    assert job_a._fault_plan.injected["nan"] == 1
    assert job_b._fault_plan.injected["dropout"] == 1

    # the on-device guard recorded the drop (dropout is a host mask
    # edit — the device guard never fires on that arm)
    assert rec_a.data.dropped_workers == [1.0, 0.0]
    assert rec_b.data.dropped_workers == [0.0, 0.0]
    assert len(rec_a.data.train_loss) == 2
    assert np.isfinite(rec_a.data.train_loss).all()

    va, _ = load_checkpoint("fnan1")
    vb, _ = load_checkpoint("fdrop1")
    for a, b in zip(jax.tree_util.tree_leaves(va),
                    jax.tree_util.tree_leaves(vb)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_job_quarantines_repeat_offender(jobenv):
    """quarantine_after=1: the worker that drops once is masked out for
    the rest of the epoch and the count lands in history; the next
    epoch starts with a clean slate."""
    job, rec = _run_faulted(
        jobenv, "fquar1", [{"kind": "nan", "epoch": 0, "worker": 2}],
        quarantine_after=1)
    assert rec.data.quarantined_workers == [1, 0]
    # exactly ONE on-device drop: after the quarantine the worker is
    # masked out host-side, so later poisoned rounds never reach it
    assert rec.data.dropped_workers == [1.0, 0.0]
    assert len(rec.data.train_loss) == 2
    assert np.isfinite(rec.data.train_loss).all()


def test_job_aborts_after_all_nonfinite_rounds(jobenv):
    """abort_after=2 with every worker non-finite every round: the job
    fails with the diagnostic instead of freezing forever."""
    job, ei = _run_faulted(jobenv, "fabort1", [{"kind": "nan"}],
                           abort_after=2, expect_raise="non-finite")
    assert ei.value.status_code == 500
    assert job.exit_err is not None


def test_syncdp_job_nan_skips_and_completes(jobenv):
    """Under syncdp a poisoned worker makes the GLOBAL gradient
    non-finite: the affected steps skip, the skips land in
    dropped_workers, and the job still completes with finite loss."""
    job, rec = _run_faulted(
        jobenv, "fsync1",
        [{"kind": "nan", "epoch": 0, "round": 0, "worker": 1}],
        engine="syncdp")
    assert rec.data.dropped_workers[0] > 0
    assert rec.data.dropped_workers[1] == 0.0
    assert len(rec.data.train_loss) == 2
    assert np.isfinite(rec.data.train_loss).all()


def test_syncdp_job_aborts_after_all_skipped_steps(jobenv):
    job, ei = _run_faulted(jobenv, "fsyncab1", [{"kind": "nan"}],
                           engine="syncdp", abort_after=2,
                           expect_raise="non-finite")
    assert ei.value.status_code == 500


def test_bad_fault_options_rejected(jobenv):
    # unparseable plan
    job, ei = _run_faulted(jobenv, "fbad1", "not json {",
                           expect_raise="invalid fault_plan")
    assert ei.value.status_code == 400
    # unknown kind surfaces the parse error, not a traceback
    _, ei = _run_faulted(jobenv, "fbad2", [{"kind": "explode"}],
                         expect_raise="invalid fault_plan")
    assert ei.value.status_code == 400
    # negative policy knobs
    _, ei = _run_faulted(jobenv, "fbad3", None, quarantine_after=-1,
                         expect_raise="must be >= 0")
    assert ei.value.status_code == 400
    # nan events need a host float batch; device_cache='on' has none
    reg, store, mesh = jobenv
    task = make_task(job_id="fbad4", epochs=1)
    task.parameters.options.fault_plan = json.dumps([{"kind": "nan"}])
    task.parameters.options.device_cache = "on"
    job = TrainJob(task, get_builtin("mlp")(hidden=16, num_classes=4),
                   ToyDataset(), mesh, registry=reg, history_store=store)
    with pytest.raises(KubeMLException, match="incompatible") as ei:
        job.train()
    assert ei.value.status_code == 400


# ------------------------------------------------ checkpoint fault paths


def _bound_plan(events, job_id):
    plan = FaultPlan.parse(events)
    plan.bind(SimpleNamespace(task=SimpleNamespace(job_id=job_id),
                              req=SimpleNamespace(resume_from=None)))
    return plan


def test_corrupt_checkpoint_event_and_next_save_repairs(tmp_home):
    variables = {"params": {"w": np.arange(4.0, dtype=np.float32)}}
    save_checkpoint("fcorr1", variables, {"model": "mlp"})
    plan = _bound_plan([{"kind": "corrupt_checkpoint"}], "fcorr1")
    plan(_round_batch(0))
    assert plan.injected["corrupt_checkpoint"] == 1
    with pytest.raises(Exception):
        load_checkpoint("fcorr1")
    # the next successful save republishes a good checkpoint
    save_checkpoint("fcorr1", variables, {"model": "mlp", "epoch": 1})
    _, manifest = load_checkpoint("fcorr1")
    assert manifest["epoch"] == 1


def test_checkpoint_crash_window_old_fallback(tmp_home):
    """A crash between save_checkpoint's two publish renames leaves only
    `.old`: readers must fall back to it, and the next save must
    republish the current dir and clean the stale `.old`/`.tmp`."""
    from kubeml_tpu.api.const import kubeml_home

    v1 = {"params": {"w": np.arange(4.0, dtype=np.float32)}}
    save_checkpoint("fwin1", v1, {"model": "mlp", "epoch": 1})
    d = os.path.join(kubeml_home(), "models", "fwin1")

    # simulate the mid-publish crash window: current renamed away, plus
    # a stale tmp dir from the dead writer
    os.rename(d, d + ".old")
    os.makedirs(d + ".tmp")
    with open(os.path.join(d + ".tmp", "junk"), "w") as f:
        f.write("x")

    vars_back, manifest = load_checkpoint("fwin1")  # served from .old
    assert manifest["epoch"] == 1
    np.testing.assert_array_equal(
        np.asarray(vars_back["params"]["w"]), v1["params"]["w"])

    v2 = {"params": {"w": np.arange(4.0, dtype=np.float32) + 1}}
    save_checkpoint("fwin1", v2, {"model": "mlp", "epoch": 2})
    assert os.path.isdir(d)
    assert not os.path.exists(d + ".old")
    assert not os.path.exists(d + ".tmp")
    vars2, manifest2 = load_checkpoint("fwin1")
    assert manifest2["epoch"] == 2
    np.testing.assert_array_equal(
        np.asarray(vars2["params"]["w"]), v2["params"]["w"])


# --------------------------------------------- control-plane satellites


def test_client_retries_transient_connection_errors(monkeypatch):
    from kubeml_tpu.control import client as client_mod

    calls, sleeps = [], []

    def fake_http(method, url, body=None, **kw):
        calls.append(url)
        if len(calls) < 3:
            raise KubeMLException("cannot reach http://x:1/train: refused",
                                  503)
        return {"id": "ok1"}

    monkeypatch.setattr(client_mod, "http_json", fake_http)
    monkeypatch.setattr(client_mod, "time",
                        SimpleNamespace(sleep=sleeps.append))
    out = client_mod._request("POST", "http://x:1/train", {})
    assert out == {"id": "ok1"}
    assert len(calls) == 3
    assert len(sleeps) == 2
    assert all(0 < s <= client_mod.RETRY_CAP_S for s in sleeps)


def test_client_does_not_retry_semantic_errors(monkeypatch):
    from kubeml_tpu.control import client as client_mod

    calls = []

    def run(exc):
        calls.clear()

        def fake_http(method, url, body=None, **kw):
            calls.append(url)
            raise exc

        monkeypatch.setattr(client_mod, "http_json", fake_http)
        monkeypatch.setattr(client_mod, "time",
                            SimpleNamespace(sleep=lambda s: None))
        with pytest.raises(KubeMLException):
            client_mod._request("GET", "http://x:1/tasks")
        return len(calls)

    # a considered 503 (capacity) is not a transport failure
    assert run(KubeMLException("all device partitions leased", 503)) == 1
    # nor is any non-503
    assert run(KubeMLException("cannot reach http://x:1/tasks: x", 500)) == 1
    # a genuinely dead endpoint exhausts the attempts, then raises
    assert run(KubeMLException("cannot reach http://x:1/tasks: refused",
                               503)) == client_mod.RETRY_ATTEMPTS


def test_scheduler_defer_backoff_is_capped(monkeypatch):
    from kubeml_tpu.control import scheduler as sched_mod

    monkeypatch.setattr(sched_mod, "DEFER_BASE_S", 0.005)
    monkeypatch.setattr(sched_mod, "DEFER_CAP_S", 0.02)
    s = sched_mod.Scheduler(ps_url="http://127.0.0.1:1")

    def always_busy(task):
        raise KubeMLException("no capacity", 503)

    monkeypatch.setattr(s, "_schedule", always_busy)
    loop = threading.Thread(target=s._schedule_loop, daemon=True)
    loop.start()
    try:
        req = TrainRequest(model_type="mlp", batch_size=8, epochs=1,
                           dataset="blobs", lr=0.1, options=TrainOptions())
        from kubeml_tpu.api.types import TrainTask
        s.queue.push(TrainTask(job_id="busy1", parameters=req,
                               parallelism=2))
        deadline = time.monotonic() + 10
        while (time.monotonic() < deadline
               and s._defer_counts.get("busy1", 0) < 6):
            time.sleep(0.005)
        # the streak kept climbing well past where the uncapped delay
        # (base * 2^n) would exceed the cap — i.e. re-probes stayed fast
        assert s._defer_counts.get("busy1", 0) >= 6
        for not_before, _task in list(s._deferred):
            assert not_before - time.monotonic() \
                <= sched_mod.DEFER_CAP_S * 1.3
        # /finish clears the streak so the id doesn't linger forever
        s._defer_counts["gone1"] = 4
        s._h_finish(SimpleNamespace(params={"taskId": "gone1"}))
        assert "gone1" not in s._defer_counts
    finally:
        s._stop.set()
        with s.queue._cv:
            s.queue._cv.notify_all()
        loop.join(timeout=5)


def test_fault_test_lint(tmp_path):
    from tools.check_fault_tests import check_file, main

    tests_dir = os.path.dirname(os.path.abspath(__file__))
    assert main(["check_fault_tests", tests_dir]) == 0

    bad = tmp_path / "test_bad_faults.py"
    bad.write_text("from kubeml_tpu.faults import FaultPlan\n"
                   "import time\n"
                   "def test_x():\n"
                   "    t = time.time()\n"
                   "    return t\n")
    violations = check_file(str(bad))
    assert violations and violations[0][2] == "time.time("
    assert main(["check_fault_tests", str(tmp_path)]) == 1

    # the token inside a comment or docstring does not trip the lint
    ok = tmp_path / "sub"
    ok.mkdir()
    clean = ok / "test_ok_faults.py"
    clean.write_text('"""Mentions FaultPlan and time.time() only in '
                     'prose."""\n'
                     "# time.time() in a comment is fine too\n"
                     "def test_y():\n"
                     "    assert True\n")
    assert check_file(str(clean)) == []
    assert main(["check_fault_tests", str(ok)]) == 0


# --------------------------------------- watchdog crash recovery (e2e)


def test_fault_crash_recovered_by_watchdog(tmp_path, tmp_home, mesh8,
                                           monkeypatch):
    """A FaultPlan crash (os._exit at epoch 1, round 0) kills the
    standalone job process at exact coordinates; the PS watchdog must
    respawn it from the epoch-0 checkpoint, the restarted incarnation
    suppresses the crash event and finishes, and the restart is visible
    in the finished History and the PS restart counters."""
    from kubeml_tpu.control.client import KubemlClient
    from kubeml_tpu.control.deployment import start_deployment
    from tests.test_control_plane import wait_history, write_blob_files

    monkeypatch.setenv("STANDALONE_JOBS", "true")
    monkeypatch.setenv("KUBEML_JOB_START_TIMEOUT", "600")
    dep = start_deployment(mesh=mesh8)
    try:
        client = KubemlClient(dep.controller_url)
        paths = write_blob_files(tmp_path)
        client.v1().datasets().create(
            "blobs", paths["xtr"], paths["ytr"], paths["xte"], paths["yte"])

        plan = json.dumps([{"kind": "crash", "epoch": 1, "round": 0}])
        req = TrainRequest(
            model_type="mlp", batch_size=32, epochs=2, dataset="blobs",
            lr=0.1,
            options=TrainOptions(default_parallelism=2, k=2,
                                 static_parallelism=True, max_restarts=1,
                                 checkpoint_every=1, goal_accuracy=200.0,
                                 fault_plan=plan))
        job_id = client.v1().networks().train(req)

        wait_history(client, job_id, timeout=420)
        # wait for /finish so the PS has stamped the restart count into
        # the stored history (and reaped the child)
        assert dep.ps.wait_for_job(job_id, timeout=120)
        history = client.v1().histories().get(job_id)
        assert history.data.restarts == 1, \
            "the injected crash was not recovered by a watchdog restart"
        # one continuous run: epoch 0 from the first incarnation's
        # checkpoint, epoch 1 from the restarted one
        assert len(history.data.train_loss) == 2
        assert np.isfinite(history.data.train_loss).all()
        # per-job series cleared at finish; the PS-lifetime total stays
        expo = dep.ps.metrics.exposition()
        assert f'jobid="{job_id}"' not in expo
        assert 'kubeml_ps_restarts_total{type="standalone"} 1' in expo
    finally:
        dep.stop()
