"""Cluster-allocator invariants: gang atomicity, priority preemption,
aging/no-starvation, tenant quotas and weighted fair sharing
(kubeml_tpu/control/cluster.py), plus the scheduler satellites that
ride along (defer-leak fix, seedable backoff jitter), the telemetry
plumbing (Prometheus families, queue-starvation health rule, the
`kubeml top` cluster pane), the bench saturation arm, and the
tools/check_sched_invariants.py lint that keeps every decision path
named here.

Everything is fake-clock driven — no wall-clock sleeps, no processes.
"""

from __future__ import annotations

import random
import time

import pytest

from kubeml_tpu.api.errors import KubeMLException
from kubeml_tpu.api.types import TrainOptions, TrainRequest, TrainTask
from kubeml_tpu.control.cluster import (CLUSTER_JOB_ID, DECISION_PATHS,
                                        ClusterAllocator, parse_tenant_spec)
from kubeml_tpu.control.httpd import Request
from kubeml_tpu.control.scheduler import (DEFER_BASE_S, DEFER_CAP_S,
                                          Scheduler)

pytestmark = pytest.mark.sched


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _alloc(pool=4, weights=None, quotas=None, aging_s=0.0, clock=None):
    return ClusterAllocator(pool, tenant_weights=weights,
                            tenant_quotas=quotas,
                            clock=clock or FakeClock(), aging_s=aging_s)


def _places(decisions):
    return [d for d in decisions if d.action == "place"]


# ------------------------------------------------------- gang atomicity


def test_gang_places_atomically_or_not_at_all():
    """A gang that fits places with ALL its lanes in one decision; one
    that doesn't fit yields no partial placement — it queues whole."""
    alloc = _alloc(pool=4)
    ds = alloc.submit("j1", lanes=3)
    (d,) = _places(ds)
    assert (d.job_id, d.lanes) == ("j1", 3)
    assert d.path == "gang-atomicity"

    ds = alloc.submit("j2", lanes=3)
    assert _places(ds) == []
    assert [d.action for d in ds] == ["queue"]
    snap = alloc.snapshot()
    # nothing partial: j2 holds zero lanes while parked
    assert snap["cluster_lanes_in_use"] == 3
    assert snap["cluster_queue_depth"] == 1

    ds = alloc.release("j1")
    (d,) = _places(ds)
    assert (d.job_id, d.lanes, d.path) == ("j2", 3, "gang-atomicity")


def test_wide_gang_holds_the_line_against_backfill():
    """A size-blocked head is NOT overtaken by narrower same-priority
    arrivals behind it (no backfill), and is NOT silently shrunk to
    whatever is free — both would break the atomicity contract."""
    clock = FakeClock()
    alloc = _alloc(pool=6, clock=clock)
    alloc.submit("j1", lanes=2)
    alloc.submit("j2", lanes=2)
    clock.advance(1.0)
    assert _places(alloc.submit("wide", lanes=5)) == []
    clock.advance(1.0)
    # two free lanes exist, but the narrow job must wait behind `wide`
    assert _places(alloc.submit("narrow", lanes=2)) == []
    assert alloc.snapshot()["cluster_queue_depth"] == 2
    # j1's exit frees 2 more lanes (4 free): still not enough for the
    # head; narrow keeps waiting behind it
    assert _places(alloc.release("j1")) == []
    # j2's exit finally seats the wide gang — whole, never shrunk
    ds = alloc.release("j2")
    assert [(d.job_id, d.lanes) for d in _places(ds)] == [("wide", 5)]


def test_duplicate_submit_rejected_and_bad_pool_rejected():
    alloc = _alloc(pool=2)
    alloc.submit("j1", lanes=1)
    with pytest.raises(ValueError):
        alloc.submit("j1", lanes=1)
    with pytest.raises(ValueError):
        ClusterAllocator(0)


# ------------------------------------------------- aging / no-starvation


def test_aging_lifts_parked_job_over_sustained_high_priority():
    """A low-priority wide gang parked behind a stream of high-priority
    work gains effective priority with queue age and eventually places
    first — the no-starvation guarantee."""
    clock = FakeClock()
    alloc = _alloc(pool=2, aging_s=10.0, clock=clock)
    alloc.submit("hi-0", priority=5, lanes=2)
    assert _places(alloc.submit("low", priority=0, lanes=2)) == []
    # a FRESH high-priority arrival shows up much later: low has been
    # parked 60s -> effective priority 0 + 6 > 5, hi-1 still at 5
    clock.advance(60.0)
    assert _places(alloc.submit("hi-1", priority=5, lanes=2)) == []
    ds = alloc.release("hi-0")
    (d,) = _places(ds)
    assert d.job_id == "low"
    assert d.path == "no-starvation"
    assert alloc.aged_grants == 1
    assert alloc.snapshot()["cluster_aged_grants_total"] == 1


def test_without_aging_high_priority_always_wins():
    clock = FakeClock()
    alloc = _alloc(pool=2, aging_s=0.0, clock=clock)
    alloc.submit("hi-0", priority=5, lanes=2)
    alloc.submit("low", priority=0, lanes=2)
    alloc.submit("hi-1", priority=5, lanes=2)
    clock.advance(3600.0)
    (d,) = _places(alloc.release("hi-0"))
    assert d.job_id == "hi-1"
    assert alloc.aged_grants == 0


# -------------------------------------------- quotas and fair sharing


def test_quota_clamps_gang_and_blocks_tenant_at_cap():
    """An explicit tenant quota clamps the gang to the tenant's room
    (the quota-clamp path); a tenant AT quota waits on its own lanes."""
    alloc = _alloc(pool=8, quotas={"teamA": 2})
    ds = alloc.submit("a1", tenant="teamA", lanes=4)
    (d,) = _places(ds)
    assert (d.lanes, d.path) == (2, "quota-clamp")
    assert alloc.quota_clamps == 1
    # teamA is at quota: its next job parks even with 6 lanes free
    assert _places(alloc.submit("a2", tenant="teamA", lanes=2)) == []
    assert alloc.snapshot()["cluster_tenant_lanes"]["teamA"] == 2


def test_over_quota_tenant_clamped_before_under_quota_held_back():
    """Ordering invariant: a quota-blocked head never holds the line —
    an under-quota tenant behind it places immediately."""
    alloc = _alloc(pool=8, quotas={"teamA": 2})
    alloc.submit("a1", tenant="teamA", lanes=2)
    # a2 parks at the HEAD of the queue (same priority, earlier enqueue)
    assert _places(alloc.submit("a2", tenant="teamA", lanes=2)) == []
    ds = alloc.submit("b1", tenant="teamB", lanes=4)
    (d,) = _places(ds)
    assert (d.job_id, d.path) == ("b1", "gang-atomicity")
    # a2 still parked; it places only when teamA lanes free
    assert alloc.snapshot()["cluster_queue_depth"] == 1
    (d,) = _places(alloc.release("a1"))
    assert d.job_id == "a2"


def test_weighted_fair_deficit_breaks_ties_toward_heavier_tenant():
    """Equal-priority parked jobs from different tenants: freed lanes
    accrue deficit by weight, so the heavier tenant places first even
    when the lighter tenant enqueued earlier."""
    clock = FakeClock()
    alloc = _alloc(pool=2, weights={"heavy": 3.0, "light": 1.0},
                   clock=clock)
    alloc.submit("running", lanes=2)
    clock.advance(1.0)
    alloc.submit("light-1", tenant="light", lanes=2)  # earlier enqueue
    clock.advance(1.0)
    alloc.submit("heavy-1", tenant="heavy", lanes=2)
    (d,) = _places(alloc.release("running"))
    assert d.job_id == "heavy-1"


def test_parse_tenant_spec():
    assert parse_tenant_spec("prod=3:6") == ("prod", 3.0, 6)
    assert parse_tenant_spec("batch=1") == ("batch", 1.0, None)
    for bad in ("noweight", "x=", "x=0", "x=1:0"):
        with pytest.raises(ValueError):
            parse_tenant_spec(bad)


# ---------------------------------------------------------- preemption


def test_preempts_cheapest_victim_only_for_strictly_higher_priority():
    """A higher-priority arrival that cannot place displaces the
    cheapest victim (lowest priority, then fewest lanes); equal
    priority never preempts."""
    alloc = _alloc(pool=4)
    alloc.submit("v-big", priority=0, lanes=3)
    alloc.submit("v-small", priority=0, lanes=1)
    # equal priority: parks without displacing anyone
    ds = alloc.submit("peer", priority=0, lanes=1)
    assert [d.action for d in ds] == ["queue"]
    assert alloc.preemptions == 0
    alloc.release("peer")

    ds = alloc.submit("hi", priority=2, lanes=1)
    preempts = [d for d in ds if d.action == "preempt"]
    (p,) = preempts
    assert p.victim == "v-small"  # fewest lanes = cheapest
    assert p.path == "preempt-cheapest"
    assert alloc.preemptions == 1
    # the victim's lanes free when its drained process actually exits
    (d,) = _places(alloc.release("v-small"))
    assert d.job_id == "hi"


def test_preemption_selects_multiple_victims_but_never_overshoots():
    """Greedy multi-victim selection stops once enough lanes are
    freeing; a second arrival rides the already-draining lanes instead
    of displacing more work."""
    alloc = _alloc(pool=4)
    alloc.submit("v1", priority=0, lanes=2)
    alloc.submit("v2", priority=0, lanes=2)
    ds = alloc.submit("hi", priority=1, lanes=4)
    assert {d.victim for d in ds if d.action == "preempt"} == {"v1", "v2"}
    assert alloc.preemptions == 2
    ds = alloc.submit("hi2", priority=1, lanes=2)
    assert [d.action for d in ds] == ["queue"]  # rides the drain
    assert alloc.preemptions == 2


def test_no_preemption_when_even_all_victims_would_not_fit():
    """If displacing every lower-priority job still can't seat the
    gang, nothing is preempted — displacement without placement would
    be pure churn."""
    alloc = _alloc(pool=4)
    alloc.submit("v1", priority=0, lanes=1)
    alloc.submit("keep", priority=9, lanes=3)
    ds = alloc.submit("hi", priority=1, lanes=3)
    assert [d.action for d in ds] == ["queue"]
    assert alloc.preemptions == 0


# -------------------------------------------------------------- resize


def test_resize_grow_clamped_by_quota_and_parked_work():
    alloc = _alloc(pool=8, quotas={"teamA": 3})
    alloc.submit("a1", tenant="teamA", lanes=2)
    ds = alloc.resize("a1", 6)
    assert ds[0].action == "resize"
    assert ds[0].lanes == 3  # quota 3 binds
    assert ds[0].path == "quota-clamp"

    alloc2 = _alloc(pool=4)
    alloc2.submit("j1", lanes=2)
    alloc2.submit("wide", lanes=4)  # parked, equal priority
    ds = alloc2.resize("j1", 4)
    assert ds[0].lanes == 2  # parked peer claims freed lanes first


def test_resize_shrink_frees_lanes_and_grants_parked_work():
    alloc = _alloc(pool=4)
    alloc.submit("j1", lanes=4)
    alloc.submit("waiting", lanes=2)
    ds = alloc.resize("j1", 2)
    assert ds[0].lanes == 2
    assert [d.job_id for d in _places(ds)] == ["waiting"]
    snap = alloc.snapshot()
    assert snap["cluster_lanes_in_use"] == 4
    assert snap["cluster_queue_depth"] == 0


def test_resize_of_unmanaged_job_passes_through():
    alloc = _alloc(pool=4)
    ds = alloc.resize("ghost", 3)
    assert [(d.action, d.lanes) for d in ds] == [("resize", 3)]


# ------------------------------------------------------------ snapshot


def test_snapshot_shape_and_counters():
    clock = FakeClock()
    alloc = _alloc(pool=4, weights={"t1": 2.0}, quotas={"t1": 2},
                   clock=clock)
    alloc.submit("j1", tenant="t1", lanes=2)
    clock.advance(5.0)
    alloc.submit("j2", tenant="t2", priority=3, lanes=4)
    snap = alloc.snapshot()
    assert snap["job_id"] == CLUSTER_JOB_ID == "cluster"
    assert snap["cluster_pool_lanes"] == 4
    assert snap["cluster_lanes_in_use"] == 2
    assert snap["cluster_running_jobs"] == 1
    assert snap["cluster_queue_by_priority"] == {"3": 1}
    assert snap["cluster_oldest_wait_s"] == 0.0  # j2 just parked
    assert snap["cluster_tenant_quota"]["t1"] == 2
    assert snap["cluster_tenant_weight"]["t1"] == 2.0
    assert snap["cluster_gang_placements_total"] == 1
    clock.advance(7.0)
    assert alloc.snapshot()["cluster_oldest_wait_s"] == 7.0


# ------------------------------------------- scheduler satellite fixes


def _task(job_id: str) -> TrainTask:
    req = TrainRequest(model_type="mlp", batch_size=16, epochs=1,
                       dataset="blobs", lr=0.1,
                       options=TrainOptions(default_parallelism=2))
    return TrainTask(job_id=job_id, parameters=req)


def _finish_req(task_id: str) -> Request:
    return Request(path=f"/finish/{task_id}", params={"taskId": task_id},
                   query={}, body=None, raw=b"")


def test_finish_drops_defer_state_and_parked_deferred_task():
    """Satellite: /finish on a job that died while capacity-deferred
    must clear BOTH its backoff streak and its parked queue entry, or
    the dead job would be re-dispatched when its backoff ripens."""
    sched = Scheduler(ps_url=None)  # never started: handlers run inline
    task = _task("deadbeef")
    sched._defer_counts[task.job_id] = 3
    sched._deferred.append((time.monotonic() + 3600.0, task))
    sched._deferred.append((time.monotonic() + 3600.0, _task("other001")))
    sched._h_finish(_finish_req(task.job_id))
    assert task.job_id not in sched._defer_counts
    assert [t.job_id for _nb, t in sched._deferred] == ["other001"]


def test_finish_in_cluster_mode_releases_parked_lanes():
    alloc = _alloc(pool=4)
    sched = Scheduler(ps_url=None, allocator=alloc)
    alloc.submit("gone0001", lanes=4)
    sched._parked["gone0001"] = _task("gone0001")
    sched._h_finish(_finish_req("gone0001"))
    assert sched._parked == {}
    assert alloc.snapshot()["cluster_lanes_in_use"] == 0


def test_defer_delay_is_deterministic_with_seeded_rng():
    """Satellite: the backoff jitter comes from an injectable RNG, so
    two schedulers seeded alike produce identical delay sequences and
    every delay stays inside the documented +/-25% envelope."""
    a = Scheduler(ps_url=None, rng=random.Random(7))
    b = Scheduler(ps_url=None, rng=random.Random(7))
    seq_a = [a._defer_delay(n) for n in range(8)]
    seq_b = [b._defer_delay(n) for n in range(8)]
    assert seq_a == seq_b
    for n, delay in enumerate(seq_a):
        base = min(DEFER_CAP_S, DEFER_BASE_S * (2 ** n))
        assert 0.75 * base <= delay <= 1.25 * base


def test_scheduler_cluster_endpoint():
    sched = Scheduler(ps_url=None, allocator=_alloc(pool=2))
    snap = sched._h_cluster(Request("/cluster", {}, {}, None, b""))
    assert snap["cluster_pool_lanes"] == 2
    bare = Scheduler(ps_url=None)
    with pytest.raises(KubeMLException) as ei:
        bare._h_cluster(Request("/cluster", {}, {}, None, b""))
    assert ei.value.status_code == 503


# ------------------------------------------------- telemetry plumbing


def test_cluster_metrics_families_and_exposition():
    """update_cluster mirrors a live snapshot into the gauges, advances
    counters by delta, zeroes drained priority levels, and the result
    passes the exposition lint."""
    from kubeml_tpu.metrics.prom import MetricsRegistry
    from tools.check_metrics import parse_exposition, validate_exposition

    alloc = _alloc(pool=4, quotas={"t1": 2})
    alloc.submit("j1", tenant="t1", lanes=2)
    alloc.submit("j2", tenant="t1", priority=2, lanes=2)  # parks: at quota
    reg = MetricsRegistry()
    reg.update_cluster(alloc.snapshot())
    text = reg.exposition()
    assert validate_exposition(text) == []

    def _flatten(families):
        return {(n, tuple(sorted(lab.items()))): v
                for f in families.values() for n, lab, v in f["samples"]}

    samples = _flatten(parse_exposition(text))
    assert samples[("kubeml_cluster_pool_lanes",
                    (("pool", "shared"),))] == 4.0
    assert samples[("kubeml_cluster_queue_depth",
                    (("priority", "2"),))] == 1.0
    assert samples[("kubeml_cluster_tenant_share",
                    (("tenant", "t1"),))] == 0.5
    assert samples[("kubeml_cluster_gang_placements_total",
                    (("pool", "shared"),))] == 1.0

    # queue drains (j1 exits, j2 places) -> priority series zeroes and
    # the counter advances by delta, not by replayed total
    alloc.release("j1")
    reg.update_cluster(alloc.snapshot())
    reg.update_cluster(alloc.snapshot())  # replay: no double count
    samples = _flatten(parse_exposition(reg.exposition()))
    assert samples[("kubeml_cluster_queue_depth",
                    (("priority", "2"),))] == 0.0
    assert samples[("kubeml_cluster_gang_placements_total",
                    (("pool", "shared"),))] == 2.0


def test_queue_starvation_health_rule():
    """The queue_starvation rule fires on a cluster snapshot whose
    oldest parked job outwaits the limit — and never on training
    samples, which carry no cluster fields."""
    from kubeml_tpu.control.health import HealthEvaluator, default_rules

    clock = FakeClock(1000.0)
    ev = HealthEvaluator(clock=clock,
                         rules=default_rules(queue_starvation_s=30.0))
    snap = {"job_id": "cluster", "cluster_pool_lanes": 4,
            "cluster_lanes_in_use": 4, "cluster_queue_depth": 1,
            "cluster_oldest_wait_s": 10.0}
    assert ev.observe(snap) == []
    snap["cluster_oldest_wait_s"] = 45.0
    fired = ev.observe(snap)
    assert [r["rule"] for r in fired] == ["queue_starvation"]
    assert ev.verdict("cluster")["state"] == "warning"
    # queue drained: the rule clears
    snap.update(cluster_queue_depth=0, cluster_oldest_wait_s=0.0)
    ev.observe(snap)
    assert ev.verdict("cluster")["state"] == "healthy"
    # a training sample can't fire it
    ev.observe({"job_id": "train1", "train_loss": 0.5,
                "epoch_duration": 100.0})
    assert ev.verdict("train1")["state"] == "healthy"


def test_top_renders_cluster_pane():
    from kubeml_tpu.cli.main import _render_top

    doc = {"id": "cluster", "state": "warning",
           "reasons": [{"rule": "queue_starvation", "severity": "warning",
                        "detail": "oldest parked job has waited 45s"}],
           "latest": {"cluster_pool_lanes": 8, "cluster_lanes_in_use": 6,
                      "cluster_running_jobs": 2, "cluster_queue_depth": 3,
                      "cluster_oldest_wait_s": 45.0,
                      "cluster_queue_by_priority": {"0": 2, "2": 1},
                      "cluster_tenant_lanes": {"prod": 4, "batch": 2},
                      "cluster_tenant_quota": {"prod": 6},
                      "cluster_preemptions_total": 1}}
    out = _render_top(doc)
    assert "cluster: lanes 6/8 (75%)" in out
    assert "queue by priority: p2:1  p0:2" in out
    assert "tenant prod" in out and "share 50%" in out
    assert "preemptions 1" in out
    assert "queue_starvation" in out
    # a training verdict renders no cluster pane
    plain = _render_top({"id": "job1", "state": "healthy", "reasons": [],
                         "latest": {"train_loss": 0.5}})
    assert "cluster:" not in plain


# ------------------------------------------------------ bench arm


def test_bench_cluster_arm_pins():
    """The saturation arm is a pure function of its job table: the
    fair/preemptive allocator beats FIFO on BOTH makespan and
    high-priority p99 queue wait, with the placement/preemption counts
    pinned and zero restart budget spent."""
    import bench

    arm = bench._measure_cluster_arm()
    assert arm["fair_makespan_s"] < arm["fifo_makespan_s"]
    assert arm["fair_high_prio_p99_wait_s"] \
        < arm["fifo_high_prio_p99_wait_s"]
    # exact pins (deterministic replay, fake clock)
    assert arm["fifo_makespan_s"] == 18.0
    assert arm["fair_makespan_s"] == 17.0
    assert arm["fifo_high_prio_p99_wait_s"] == 12.0
    assert arm["fair_high_prio_p99_wait_s"] == 1.0
    assert arm["gang_placements"] == 8
    assert arm["preemptions"] == 1
    assert arm["preempt_requeues"] == 1
    assert arm["restart_budget_spent"] == 0


# ------------------------------------------------------------ the lint


def test_sched_invariants_lint_passes_and_self_checks(tmp_path):
    """tools/check_sched_invariants.py: green on this repo (this very
    file names every decision path in assertions), and its coverage
    primitive distinguishes assertions from comments and input tables."""
    from tools import check_sched_invariants as lint

    assert lint.main(["check_sched_invariants.py"]) == 0
    names = lint.decision_paths("kubeml_tpu/control/cluster.py")
    assert set(names) == set(DECISION_PATHS) == {
        "gang-atomicity", "no-starvation", "quota-clamp",
        "preempt-cheapest", "serve-elastic"}

    covered = tmp_path / "test_ok.py"
    covered.write_text("def test_x(d):\n"
                       "    assert d.path == 'gang-atomicity'\n")
    assert lint.file_covers(str(covered), "gang-atomicity")
    # a comment mention or a bare input table must NOT count
    uncovered = tmp_path / "test_no.py"
    uncovered.write_text("# talks about 'gang-atomicity' only\n"
                         "PATHS = ['gang-atomicity']\n"
                         "def test_y():\n"
                         "    assert True\n")
    assert not lint.file_covers(str(uncovered), "gang-atomicity")
    # a missing path fails the run against a synthetic tests dir
    root = tmp_path / "fakerepo"
    (root / "kubeml_tpu" / "control").mkdir(parents=True)
    (root / "tests").mkdir()
    (root / "kubeml_tpu" / "control" / "cluster.py").write_text(
        'DECISION_PATHS = {"gang-atomicity": "x", "quota-clamp": "y"}\n')
    (root / "tests" / "test_some.py").write_text(
        "def test_z(d):\n    assert d.path == 'quota-clamp'\n")
    assert lint.main(["lint", str(root)]) == 1
