"""ResNet/VGG zoo: shapes, variable collections, one engine round each.

Parity targets: function_resnet34.py / function_vgg11.py / resnet32.py in
the reference experiments, plus BASELINE configs resnet18 and resnet50.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeml_tpu.models import builtin_names, get_builtin
from kubeml_tpu.parallel.kavg import KAvgEngine

VISION = ["resnet18", "resnet32", "resnet34", "resnet50", "vgg11"]


def test_zoo_registered():
    names = builtin_names()
    for n in VISION + ["lenet"]:
        assert n in names, names


@pytest.mark.parametrize("name,hw", [("resnet18", 32), ("resnet32", 32),
                                     ("vgg11", 32), ("resnet50", 64)])
def test_forward_shapes(name, hw):
    model = get_builtin(name)()
    x = jnp.zeros((2, hw, hw, 3))
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x})
    logits = model.module.apply(variables, x, train=False)
    assert logits.shape == (2, model.num_classes)
    assert logits.dtype == jnp.float32
    if name.startswith("resnet"):
        assert "batch_stats" in variables  # BatchNorm statistics collection


def test_resnet18_engine_round(mesh8):
    """One sync round through the K-avg engine with BatchNorm state."""
    rng = np.random.RandomState(0)
    model = get_builtin("resnet18")()
    W, S, B = 8, 1, 4
    x = rng.rand(W, S, B, 32, 32, 3).astype(np.float32)
    y = rng.randint(0, 10, size=(W, S, B)).astype(np.int32)
    variables = model.init_variables(
        jax.random.PRNGKey(0), {"x": jnp.asarray(x[0, 0])})
    engine = KAvgEngine(mesh8, model.loss, model.metrics,
                        model.configure_optimizers, donate=False)
    batch = {"x": jnp.asarray(x), "y": jnp.asarray(y)}
    rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)
    new_vars, stats = engine.train_round(
        variables, batch, sample_mask=np.ones((W, S, B)),
        step_mask=np.ones((W, S)), worker_mask=np.ones(W),
        rngs=rngs, lr=0.01, epoch=0)
    assert stats.contributors == 8.0
    # params actually moved and batch_stats were updated + averaged
    p0 = jax.tree_util.tree_leaves(variables["params"])
    p1 = jax.tree_util.tree_leaves(new_vars["params"])
    assert any(not np.allclose(np.asarray(a), np.asarray(b))
               for a, b in zip(p0, p1))
    s0 = jax.tree_util.tree_leaves(variables["batch_stats"])
    s1 = jax.tree_util.tree_leaves(new_vars["batch_stats"])
    assert any(not np.allclose(np.asarray(a), np.asarray(b))
               for a, b in zip(s0, s1))


def test_resnet_lr_schedule_steps():
    """The epoch-stepped LR decay (reference function_resnet34.py:51-60
    semantics): updates shrink after the decay boundary."""
    model = get_builtin("resnet18")()
    grads = {"w": jnp.ones((4,))}
    params = {"w": jnp.zeros((4,))}

    def step_mag(epoch):
        tx = model.configure_optimizers(jnp.float32(0.1), jnp.int32(epoch))
        state = tx.init(params)
        updates, _ = tx.update(grads, state, params)
        return float(jnp.abs(updates["w"]).max())

    assert step_mag(20) == pytest.approx(step_mag(0) * 0.1, rel=1e-4)
    assert step_mag(30) == pytest.approx(step_mag(0) * 0.01, rel=1e-4)
