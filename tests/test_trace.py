"""Tracing subsystem: span accounting + per-epoch summaries in job logs."""

import re

from kubeml_tpu.utils.trace import Tracer, xla_profile


def test_tracer_spans_and_summary():
    tr = Tracer()
    with tr.span("a"):
        pass
    with tr.span("a"):
        pass
    tr.add("b", 0.5)
    s = tr.summary()
    assert s["a"]["count"] == 2
    assert s["b"]["total_s"] == 0.5
    txt = tr.format_summary()
    assert "a=" in txt and "b=0.500s/1" in txt
    assert tr.reset()["a"]["count"] == 2
    assert tr.summary() == {}


def test_xla_profile_noop_safe(tmp_path):
    # must not raise even if the backend lacks profiler support
    with xla_profile(str(tmp_path / "prof")):
        import jax.numpy as jnp
        jnp.ones(4).sum()


def test_xla_profile_fallback_on_start_failure(tmp_path, monkeypatch,
                                               caplog):
    # start_trace failure: warn, run the block, and never call stop_trace
    import jax

    def boom(*a, **k):
        raise RuntimeError("no profiler here")

    stopped = []
    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: stopped.append(True))
    ran = []
    with caplog.at_level("WARNING", logger="kubeml_tpu.trace"):
        with xla_profile(str(tmp_path / "prof")):
            ran.append(True)
    assert ran and not stopped
    assert "could not start trace" in caplog.text


def test_job_logs_trace_summary(tmp_path, tmp_home, mesh8):
    from tests.test_job import ToyDataset, make_blobs, make_task
    from kubeml_tpu.data.registry import DatasetRegistry
    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.train.job import TrainJob

    reg = DatasetRegistry()
    make_blobs(reg)
    log = tmp_path / "job.log"
    job = TrainJob(make_task(job_id="tracejob1", epochs=2),
                   get_builtin("mlp")(hidden=16, num_classes=4),
                   ToyDataset(), mesh8, registry=reg, log_file=str(log))
    job.train()
    text = log.read_text()
    # every epoch line carries the phase breakdown (the cache_upload
    # span precedes it on epochs where the device dataset cache laid
    # out or verified its slabs)
    assert len(re.findall(
        r"\[(?:cache_upload=\S+ )?data_wait=\S+ device_drain=\S+ "
        r"dispatch=\S+\]", text)) == 2
