"""Tracing subsystem: span accounting, Chrome-trace timelines, per-epoch
summaries in job logs, and the per-job trace directory + merger."""

import json
import re
import threading

import pytest

from kubeml_tpu.utils.trace import (TraceSink, Tracer, get_trace_context,
                                    make_trace_id, merge_job_trace,
                                    trace_context, trace_dir, xla_profile)


class FakeClock:
    """Advances 1.0s on every read — span trees become exact."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 1.0
        return self.t


def test_tracer_spans_and_summary():
    tr = Tracer()
    with tr.span("a"):
        pass
    with tr.span("a"):
        pass
    tr.add("b", 0.5)
    s = tr.summary()
    assert s["a"]["count"] == 2
    assert s["b"]["total_s"] == 0.5
    txt = tr.format_summary()
    assert "a=" in txt and "b=0.500s/1" in txt
    assert tr.reset()["a"]["count"] == 2
    assert tr.summary() == {}


def test_fake_clock_exact_span_tree():
    """Injected clock -> deterministic timeline: exact ts/dur in µs,
    parent links following the per-thread nesting, caller args (including
    ones attached mid-span through the yielded dict) on the event."""
    tid = make_trace_id()
    tr = Tracer(clock=FakeClock(), trace_id=tid)
    with tr.span("epoch", epoch=0):
        with tr.span("round", round=0):
            with tr.span("dispatch") as sp:
                sp["workers"] = 4
    ev = {e["name"]: e for e in tr.events()}
    # clock reads: epoch@1, round@2, dispatch@3, then ends at 4, 5, 6
    assert ev["dispatch"]["ts"] == 3_000_000
    assert ev["dispatch"]["dur"] == 1_000_000
    assert ev["round"]["ts"] == 2_000_000
    assert ev["round"]["dur"] == 3_000_000
    assert ev["epoch"]["ts"] == 1_000_000
    assert ev["epoch"]["dur"] == 5_000_000
    assert all(e["ph"] == "X" for e in ev.values())
    assert ev["dispatch"]["args"] == {"trace_id": tid, "parent": "round",
                                      "workers": 4}
    assert ev["round"]["args"]["parent"] == "epoch"
    assert "parent" not in ev["epoch"]["args"]
    assert ev["epoch"]["args"]["epoch"] == 0
    assert tr.summary()["epoch"] == {"count": 1, "total_s": 5.0,
                                     "mean_s": 5.0}


def test_reset_keeps_timeline_events():
    tr = Tracer(clock=FakeClock())
    with tr.span("a"):
        pass
    tr.reset()
    with tr.span("b"):
        pass
    assert tr.summary() == {"b": {"count": 1, "total_s": 1.0,
                                  "mean_s": 1.0}}
    assert [e["name"] for e in tr.events()] == ["a", "b"]


def test_event_cap_drops_but_keeps_summary():
    tr = Tracer(clock=FakeClock(), max_events=2)
    for _ in range(3):
        with tr.span("a"):
            pass
    assert len(tr.events()) == 2
    assert tr.dropped_events == 1
    assert tr.summary()["a"]["count"] == 3  # the log summary never drops


def test_tracer_thread_safety():
    """Concurrent spans from many threads: no lost updates, and parent
    links never cross threads (each thread has its own nesting stack)."""
    tr = Tracer()
    n_threads, n_spans = 8, 200

    def work():
        for _ in range(n_spans):
            with tr.span("outer"):
                with tr.span("inner"):
                    pass

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    s = tr.summary()
    assert s["outer"]["count"] == n_threads * n_spans
    assert s["inner"]["count"] == n_threads * n_spans
    inner = [e for e in tr.events() if e["name"] == "inner"]
    assert len(inner) == n_threads * n_spans
    assert all(e["args"]["parent"] == "outer" for e in inner)


def test_trace_context_binds_and_restores():
    assert get_trace_context() is None
    with trace_context("aaaa000011112222"):
        assert get_trace_context() == "aaaa000011112222"
        with trace_context("bbbb000011112222"):
            assert get_trace_context() == "bbbb000011112222"
        assert get_trace_context() == "aaaa000011112222"
    assert get_trace_context() is None


def test_trace_sink_and_merge(tmp_home):
    tid = make_trace_id()
    t1 = Tracer(clock=FakeClock(), trace_id=tid)
    with t1.span("ps.start_task"):
        pass
    t2 = Tracer(clock=FakeClock(), trace_id=tid)
    with t2.span("epoch"):
        pass
    TraceSink("mergejob1", "ps").write(t1)
    path = TraceSink("mergejob1", "job").write(t2)
    assert json.load(open(path))["metadata"]["trace_id"] == tid
    # a torn/foreign file in the directory is skipped, not fatal
    with open(f"{trace_dir('mergejob1')}/bad.trace.json", "w") as f:
        f.write("{not json")
    doc = merge_job_trace("mergejob1")
    assert sorted(doc["metadata"]["sources"]) == [
        f"job-{__import__('os').getpid()}.trace.json",
        f"ps-{__import__('os').getpid()}.trace.json"]
    assert doc["metadata"]["trace_ids"] == [tid]
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in spans} == {"ps.start_task", "epoch"}
    assert all(e["args"]["trace_id"] == tid for e in spans)
    procs = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M"}
    assert procs == {"ps:mergejob1", "job:mergejob1"}
    with pytest.raises(FileNotFoundError):
        merge_job_trace("nosuchjob1")


def test_xla_profile_noop_safe(tmp_path):
    # must not raise even if the backend lacks profiler support
    with xla_profile(str(tmp_path / "prof")):
        import jax.numpy as jnp
        jnp.ones(4).sum()


def test_xla_profile_fallback_on_start_failure(tmp_path, monkeypatch,
                                               caplog):
    # start_trace failure: warn, run the block, and never call stop_trace
    import jax

    def boom(*a, **k):
        raise RuntimeError("no profiler here")

    stopped = []
    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: stopped.append(True))
    ran = []
    with caplog.at_level("WARNING", logger="kubeml_tpu.trace"):
        with xla_profile(str(tmp_path / "prof")):
            ran.append(True)
    assert ran and not stopped
    assert "could not start trace" in caplog.text


def test_job_logs_trace_summary(tmp_path, tmp_home, mesh8):
    from tests.test_job import ToyDataset, make_blobs, make_task
    from kubeml_tpu.data.registry import DatasetRegistry
    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.train.job import TrainJob

    reg = DatasetRegistry()
    make_blobs(reg)
    log = tmp_path / "job.log"
    job = TrainJob(make_task(job_id="tracejob1", epochs=2),
                   get_builtin("mlp")(hidden=16, num_classes=4),
                   ToyDataset(), mesh8, registry=reg, log_file=str(log))
    job.train()
    text = log.read_text()
    # every epoch line carries the phase breakdown (the cache_upload
    # span precedes it on epochs where the device dataset cache laid
    # out or verified its slabs)
    assert len(re.findall(
        r"\[(?:cache_upload=\S+ )?data_wait=\S+ dispatch=\S+ "
        r"epoch=\S+ (?:merge_overlap=\S+ )?merge_wait=\S+ "
        r"round=\S+\]", text)) == 2

    # the same run left a whole-job Chrome timeline in the trace dir:
    # one trace id, round spans nested under epoch spans, dispatch
    # spans nested under rounds
    doc = merge_job_trace("tracejob1")
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    tids = doc["metadata"]["trace_ids"]
    assert len(tids) == 1 and job.task.trace_id == tids[0]
    assert all(e["args"]["trace_id"] == tids[0] for e in spans)
    epochs = [e for e in spans if e["name"] == "epoch"]
    assert [e["args"]["epoch"] for e in epochs] == [0, 1]
    rounds = [e for e in spans if e["name"] == "round"]
    assert rounds and all(e["args"]["parent"] == "epoch" for e in rounds)
    # the exhaustion probe round carries the tail marker, real rounds
    # carry their worker count
    assert [e for e in rounds if e["args"].get("tail")]
    assert [e for e in rounds if e["args"].get("workers")]
    dispatches = [e for e in spans if e["name"] == "dispatch"]
    assert dispatches
    assert all(e["args"]["parent"] == "round" for e in dispatches)
