"""Continual-training plane tests (streaming ingest -> sliding-window
training -> zero-downtime serving hot-swap).

The contracts pinned here:

  * ingest — appends are generation-tagged, atomically committed, and
    validated before anything touches disk state (shape/dtype drift and
    non-monotonic producer tags are 400s); retention drops whole windows
    from the FRONT and advances the absolute `base` coordinate
  * sliding window — a continual job re-polls the registry between
    epochs and trains the fresh window under the SAME loop; the device
    cache refreshes incrementally with slabs bit-identical to a cold
    layout; an injected `stale_data` fault makes the freshness lag grow
    deterministically and the data_staleness health rule fire
  * hot-swap — every SWAP_PATH_VARIANTS entry in serve/engine.py keeps
    a named test below (tools/check_swap_safety.py lints that): streams
    pinned at attach decode bit-identically to a solo run on their
    generation across swaps, the prefix cache never serves a page
    across generations, and a retired generation's weights and cache
    partition actually free — with the decode program compiled once
  * restartability — a continual job preempted mid-window resumes from
    its round cursor and finishes bit-identical to an uninterrupted run
    over the same generation sequence
"""

import json
import time

import numpy as np
import pytest

from kubeml_tpu.api.errors import (InvalidFormatError, JobPreemptedError,
                                   KubeMLException)
from kubeml_tpu.data.registry import DatasetRegistry
from kubeml_tpu.models import get_builtin
from kubeml_tpu.train.checkpoint import load_checkpoint
from kubeml_tpu.train.history import HistoryStore
from kubeml_tpu.train.job import JobCallbacks, TrainJob

from tests.test_job import ToyDataset, make_task

pytestmark = pytest.mark.continual

DIM, CLASSES, SUBSET = 8, 4, 16


def _split(n, seed):
    rng = np.random.RandomState(seed)
    y = rng.randint(0, CLASSES, n).astype(np.int32)
    x = rng.randn(n, DIM).astype(np.float32) * 2.0
    x[np.arange(n), y % DIM] += 3.0
    return x, y


def _blobs(reg, n_train=256, n_test=64, seed=0, subset=SUBSET):
    xtr, ytr = _split(n_train, seed)
    xte, yte = _split(n_test, seed + 1)
    return reg.create("blobs", xtr, ytr, xte, yte, subset_size=subset)


def _continual_job(reg, mesh, job_id, *, epochs, store=None,
                   callbacks=None, resume=False, **optkw):
    task = make_task(job_id=job_id, epochs=epochs, parallelism=2, k=1,
                     batch=16, goal=200.0)
    task.parameters.options.continual = True
    for key, val in optkw.items():
        setattr(task.parameters.options, key, val)
    if resume:
        task.parameters.resume_from = job_id
    model = get_builtin("mlp")(hidden=16, num_classes=CLASSES)
    return TrainJob(task, model, ToyDataset(), mesh, registry=reg,
                    history_store=store, callbacks=callbacks)


# ---------------------------------------------------------------- ingest


def test_append_advances_generation_and_windowed_view(tmp_home):
    reg = DatasetRegistry()
    h = _blobs(reg, n_train=256)
    assert (h.generation, h.train_base, h.train_offset) == (1, 0, 0)

    xa, ya = _split(128, seed=7)
    h2 = reg.append("blobs", xa, ya)
    assert h2.generation == 2
    assert h2.train_samples == 384 and h2.train_base == 0

    # the committed bytes: old content untouched, the chunk at the tail
    x_all, y_all = (np.asarray(a) for a in h2.train_arrays())
    np.testing.assert_array_equal(x_all[256:], xa)
    np.testing.assert_array_equal(y_all[256:], ya)

    # a windowed view over the newest generation only, doc-aligned
    hw = reg.get("blobs", window_generations=1)
    assert hw.train_samples == 128
    assert hw.train_offset == 256 and hw.train_base == 256
    xw, yw = (np.asarray(a) for a in hw.train_arrays())
    np.testing.assert_array_equal(xw, xa)
    np.testing.assert_array_equal(yw, ya)

    # a window wider than history degrades to the full dataset
    assert reg.get("blobs", window_generations=9).train_samples == 384


def test_append_retention_drops_front_and_advances_base(tmp_home):
    reg = DatasetRegistry()
    _blobs(reg, n_train=256)
    xa, ya = _split(128, seed=7)
    h2 = reg.append("blobs", xa, ya, retention_generations=2)
    assert h2.train_samples == 384 and h2.train_base == 0  # 2 windows kept

    xb, yb = _split(64, seed=8)
    h3 = reg.append("blobs", xb, yb, retention_generations=2)
    # generation-1's 256 samples expired from the front
    assert h3.generation == 3
    assert h3.train_samples == 192 and h3.train_base == 256
    x_all, y_all = (np.asarray(a) for a in h3.train_arrays())
    np.testing.assert_array_equal(x_all, np.concatenate([xa, xb]))
    np.testing.assert_array_equal(y_all, np.concatenate([ya, yb]))


def test_append_validation_400s_commit_nothing(tmp_home):
    reg = DatasetRegistry()
    _blobs(reg, n_train=256)
    x, y = _split(64, seed=7)
    bad = [
        (x[:, :4], y),                        # sample shape drift
        (x.astype(np.float64), y),            # data dtype drift
        (x, y.astype(np.int64)),              # label dtype drift
        (x[:0], y[:0]),                       # empty chunk
        (x, y[:32]),                          # length mismatch
    ]
    for xb, yb in bad:
        with pytest.raises(InvalidFormatError) as ei:
            reg.append("blobs", xb, yb)
        assert ei.value.status_code == 400
    # a stale producer tag (optimistic concurrency) is a 400 too
    with pytest.raises(InvalidFormatError):
        reg.append("blobs", x, y, generation=1)
    # nothing committed: still generation 1, original sample count
    h = reg.get("blobs")
    assert (h.generation, h.train_samples) == (1, 256)


def test_dataset_append_route_e2e(tmp_path, tmp_home, mesh8):
    """Client -> controller -> storage over real HTTP: append commits a
    new generation, validation failures come back as 400 envelopes."""
    from kubeml_tpu.control.client import KubemlClient
    from kubeml_tpu.control.deployment import start_deployment

    dep = start_deployment(mesh=mesh8)
    try:
        client = KubemlClient(dep.controller_url)
        paths = {}
        xtr, ytr = _split(256, seed=0)
        xte, yte = _split(64, seed=1)
        xa, ya = _split(128, seed=7)
        for name, arr in (("xtr", xtr), ("ytr", ytr), ("xte", xte),
                          ("yte", yte), ("xa", xa), ("ya", ya)):
            p = tmp_path / f"{name}.npy"
            np.save(p, arr)
            paths[name] = str(p)
        client.v1().datasets().create("blobs", paths["xtr"], paths["ytr"],
                                      paths["xte"], paths["yte"])
        out = client.v1().datasets().append(
            "blobs", paths["xa"], paths["ya"], retention=4)
        assert out["generation"] == 2
        assert out["train_set_size"] == 384

        # non-monotonic producer tag -> 400, nothing committed
        with pytest.raises(KubeMLException) as ei:
            client.v1().datasets().append("blobs", paths["xa"],
                                          paths["ya"], generation=1)
        assert ei.value.status_code == 400
        # dtype drift -> 400
        p64 = tmp_path / "x64.npy"
        np.save(p64, xa.astype(np.float64))
        with pytest.raises(KubeMLException) as ei:
            client.v1().datasets().append("blobs", str(p64), paths["ya"])
        assert ei.value.status_code == 400
        # unknown dataset -> 404
        with pytest.raises(KubeMLException) as ei:
            client.v1().datasets().append("nosuch", paths["xa"],
                                          paths["ya"])
        assert ei.value.status_code == 404
        assert [s.train_set_size
                for s in client.v1().datasets().list()] == [384]
    finally:
        dep.stop()


# ----------------------------------------------------------- device cache


def test_incremental_cache_bit_identical_on_grow_and_slide(tmp_home, mesh8):
    """The incremental slab refresh (absolute-range overlap reuse) is
    bit-identical to a cold layout for both a grown window (append) and
    a slid window (retention drop), and no-ops on an unchanged one."""
    from kubeml_tpu.data.device_cache import DeviceDatasetCache
    from kubeml_tpu.data.sharding import plan_epoch

    reg = DatasetRegistry()
    _blobs(reg, n_train=256)
    W = 2

    def plan_for(h):
        return plan_epoch(h.train_samples, W, 1, 16, h.subset_size)

    def assert_matches_cold(inc, h):
        cold = DeviceDatasetCache(h, mesh8, layout="sharded",
                                  grow_quantum=inc.grow_quantum)
        cold.ensure(plan_for(h), W)
        for key in ("x", "y"):
            np.testing.assert_array_equal(np.asarray(inc.arrays[key]),
                                          np.asarray(cold.arrays[key]))

    h1 = reg.get("blobs")
    inc = DeviceDatasetCache(h1, mesh8, layout="sharded",
                             incremental=True, grow_quantum=64)
    assert inc.ensure(plan_for(h1), W)
    assert inc.stats["uploads"] == 1

    # grow: an append extends every lane's absolute range
    reg.append("blobs", *_split(128, seed=7))
    h2 = reg.get("blobs")
    inc.refresh(h2)
    assert inc.ensure(plan_for(h2), W)
    assert_matches_cold(inc, h2)

    # slide: retention expires the front, base advances
    reg.append("blobs", *_split(64, seed=8), retention_generations=2)
    h3 = reg.get("blobs")
    assert h3.train_base == 256
    inc.refresh(h3)
    assert inc.ensure(plan_for(h3), W)
    assert_matches_cold(inc, h3)
    assert inc.stats["uploads"] == 3

    # unchanged window: ensure is a no-op
    inc.refresh(reg.get("blobs"))
    assert inc.ensure(plan_for(h3), W) is False
    assert inc.stats["uploads"] == 3


def test_replicated_cache_reuploads_after_refresh(tmp_home, mesh8):
    """The replicated layout keys its upload-once guard on the handle's
    absolute window — a continual refresh that grew the dataset must
    re-upload (the old existence-only guard froze generation 1)."""
    from kubeml_tpu.data.device_cache import DeviceDatasetCache

    reg = DatasetRegistry()
    _blobs(reg, n_train=256)
    h1 = reg.get("blobs")
    cache = DeviceDatasetCache(h1, mesh8, layout="replicated")
    assert cache.ensure()
    assert cache.ensure() is False          # unchanged window: no-op
    reg.append("blobs", *_split(64, seed=7))
    cache.refresh(reg.get("blobs"))
    assert cache.ensure()                   # window moved: re-upload
    x, _ = (np.asarray(a) for a in reg.get("blobs").train_arrays())
    np.testing.assert_array_equal(np.asarray(cache.arrays["x"]), x)


# ------------------------------------------------------- sliding window


def test_continual_job_follows_appends(tmp_home, mesh8):
    """Appends land between epochs; the job's freshness pair tracks the
    registry with zero lag (epoch N+1 trains the generation committed
    during epoch N's publish)."""
    reg = DatasetRegistry()
    _blobs(reg)
    store = HistoryStore()
    seen = []

    def publish(m):
        seen.append((m.dataset_generation, m.data_lag_generations))
        if len(seen) <= 2:
            reg.append("blobs", *_split(64, seed=10 + len(seen)))

    job = _continual_job(reg, mesh8, "ctfollow1", epochs=4, store=store,
                         callbacks=JobCallbacks(publish_metrics=publish))
    record = job.train()
    assert seen == [(1, 0), (2, 0), (3, 0), (3, 0)]
    assert len(record.data.train_loss) == 4
    # the job stays checkpointed/inferable like any other
    variables, manifest = load_checkpoint("ctfollow1")
    assert manifest["job_id"] == "ctfollow1"


def test_continual_refresh_survives_registry_failure(tmp_home, mesh8):
    """A transient registry failure at the epoch boundary keeps the
    current window (and the job alive) instead of failing the run."""
    reg = DatasetRegistry()
    _blobs(reg)
    seen = []

    real_get = reg.get

    def flaky_get(name, window_generations=0):
        if seen and len(seen) == 1:
            raise OSError("registry briefly unreadable")
        return real_get(name, window_generations=window_generations)

    def publish(m):
        seen.append((m.dataset_generation, m.data_lag_generations))

    reg.get = flaky_get
    job = _continual_job(reg, mesh8, "ctflaky1", epochs=3,
                         callbacks=JobCallbacks(publish_metrics=publish))
    job.train()
    assert job.task.state == "finished"
    assert seen == [(1, 0), (1, 0), (1, 0)]


def test_stale_data_fault_drives_staleness_rule(tmp_home, mesh8):
    """The `stale_data` fault suppresses the epoch-boundary refresh, so
    the registry pulls ahead deterministically; the data_staleness
    health rule fires past the lag limit and stays quiet for
    non-continual samples."""
    from kubeml_tpu.control.health import default_rules

    reg = DatasetRegistry()
    _blobs(reg)
    seen = []

    def publish(m):
        seen.append((m.dataset_generation, m.data_lag_generations))
        if len(seen) <= 3:
            reg.append("blobs", *_split(64, seed=10 + len(seen)))

    job = _continual_job(
        reg, mesh8, "ctstale1", epochs=5,
        callbacks=JobCallbacks(publish_metrics=publish),
        fault_plan=json.dumps([{"kind": "stale_data"}]))
    job.train()
    # trained generation pinned at 1, lag grows with each append
    assert seen == [(1, 0), (1, 1), (1, 2), (1, 3), (1, 3)]
    assert job._fault_plan.injected["stale_data"] == 5

    rule = {r.name: r for r in default_rules()}["data_staleness"]
    detail = rule.check([{"dataset_generation": 1,
                          "data_lag_generations": 3}])
    assert detail and "3 generation(s) ahead" in detail
    assert rule.check([{"data_lag_generations": 2}]) is None  # at limit
    assert rule.check([{"data_lag_generations": -1}]) is None  # wire default
    assert rule.check([{}]) is None                # pre-continual samples


def test_continual_window_generations_slides_training_window(tmp_home,
                                                             mesh8):
    """window_generations caps the trained window: after retention +
    appends the job's loader sees only the newest generations (doc
    aligned), not the whole retained set."""
    reg = DatasetRegistry()
    _blobs(reg)
    reg.append("blobs", *_split(128, seed=7))

    job = _continual_job(reg, mesh8, "ctwin1", epochs=1, window_generations=1)
    job.train()
    assert job._handle.train_samples == 128
    assert job._handle.train_offset == 256


def test_continual_option_validation_400s(tmp_home, mesh8):
    """Misconfigured continual options 400 before any data loads."""
    cases = [
        (dict(window_generations=-1), "must be >= 0"),
        (dict(publish_every_rounds=-1), "must be >= 0"),
        (dict(window_generations=2), "require"),
        (dict(publish_every_rounds=2), "require"),
        (dict(continual=True, publish_every_rounds=2, engine="syncdp"),
         "kavg"),
    ]
    reg = DatasetRegistry()
    _blobs(reg)
    for optkw, needle in cases:
        task = make_task(job_id="ctbad1", epochs=2)
        for key, val in optkw.items():
            setattr(task.parameters.options, key, val)
        model = get_builtin("mlp")(hidden=16, num_classes=CLASSES)
        job = TrainJob(task, model, ToyDataset(), mesh8, registry=reg)
        with pytest.raises(KubeMLException) as ei:
            job.train()
        assert ei.value.status_code == 400
        assert needle in ei.value.message


def test_mid_window_restart_resumes_bit_identical(tmp_path, tmp_home,
                                                  mesh8):
    """A continual job preempted mid-window (after a generation slide)
    resumes from its round cursor and finishes with weights
    bit-identical to an uninterrupted run over the same generation
    sequence (each run gets its own registry root so both replay
    create -> train gen 1 -> append gen 2 -> train gen 2)."""
    import jax

    def run(tag, interrupt):
        reg = DatasetRegistry(root=str(tmp_path / f"reg-{tag}"))
        _blobs(reg)
        job_id = f"ctres{tag}"
        optkw = dict(checkpoint_every_rounds=2)
        if interrupt:
            optkw["fault_plan"] = json.dumps(
                [{"kind": "preempt", "epoch": 1, "round": 3}])

        def publish(m):
            if reg.get("blobs").generation == 1:
                reg.append("blobs", *_split(64, seed=77))

        cb = JobCallbacks(publish_metrics=publish)
        job = _continual_job(reg, mesh8, job_id, epochs=2, callbacks=cb,
                             **optkw)
        if interrupt:
            with pytest.raises(JobPreemptedError):
                job.train()
            assert job.task.state == "preempted"
            _, manifest = load_checkpoint(job_id)
            ts = manifest["train_state"]
            assert (ts["epoch"], ts["round"]) == (1, 4)
            resumed = _continual_job(reg, mesh8, job_id, epochs=2,
                                     callbacks=cb, resume=True, **optkw)
            resumed.train()
            assert resumed.task.state == "finished"
        else:
            job.train()
        variables, _ = load_checkpoint(job_id)
        return [np.asarray(l)
                for l in jax.tree_util.tree_leaves(variables)]

    clean = run("a", interrupt=False)
    resumed = run("b", interrupt=True)
    assert len(clean) == len(resumed)
    for la, lb in zip(clean, resumed):
        np.testing.assert_array_equal(la, lb)


# ------------------------------------------------------------- hot-swap


def _nano(key=0):
    import jax

    model = get_builtin("gpt-nano")()
    module = model.module
    variables = model.init_variables(
        jax.random.PRNGKey(key),
        {"x": np.ones((1, module.max_len), np.int32)})
    return module, variables


def _drive(engine, limit=10_000):
    finished = []
    while engine.active():
        finished.extend(engine.step())
        limit -= 1
        assert limit > 0, "engine failed to drain"
    return finished


def _step_until(engine, pred, limit=10_000):
    while not pred():
        engine.step()
        limit -= 1
        assert limit > 0, "engine never reached the awaited state"


def _solo_tokens(module, variables, prompt, n_new, **engine_kw):
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    engine = DecodeEngine(module, variables, **engine_kw)
    req = GenerateRequest(list(prompt), max_new_tokens=n_new)
    engine.attach(req)
    _drive(engine)
    assert req.outcome == "ok"
    return req.tokens


def test_swap_attach_old_and_new_generations_bit_identical():
    """Streams attached before a swap decode the OLD weights to the
    end; streams admitted after decode the new ones — both
    bit-identical to a solo engine on their generation, with the decode
    program compiled exactly once across the swap."""
    from kubeml_tpu.serve.engine import SWAP_PATH_VARIANTS, DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    assert "swap_attach_old" in SWAP_PATH_VARIANTS
    assert "swap_attach_new" in SWAP_PATH_VARIANTS
    module, v1 = _nano(0)
    _, v2 = _nano(1)

    engine = DecodeEngine(module, v1, slots=4, page=4)
    old = GenerateRequest([5, 6, 7], max_new_tokens=8)
    engine.attach(old)
    _step_until(engine, lambda: len(old.tokens) >= 2)

    assert engine.install_weights(v2) == 2
    assert engine.active_generations() == [1, 2]
    new = GenerateRequest([9, 10, 11], max_new_tokens=6)
    engine.attach(new)
    _drive(engine)

    assert old.outcome == "ok" and new.outcome == "ok"
    np.testing.assert_array_equal(
        old.tokens, _solo_tokens(module, v1, [5, 6, 7], 8,
                                 slots=4, page=4))
    np.testing.assert_array_equal(
        new.tokens, _solo_tokens(module, v2, [9, 10, 11], 6,
                                 slots=4, page=4))
    # different inits really decode differently (the swap is observable)
    assert old.tokens != _solo_tokens(module, v2, [5, 6, 7], 8,
                                      slots=4, page=4)
    # compile pinning: the per-generation dispatch reuses the same two
    # compiled programs — a swap is data, not a new program
    assert engine.stats["compiles"] == 1
    assert engine.stats["weight_swaps"] == 1


def test_swap_mid_stream_never_changes_inflight_tokens():
    """A swap landing between two decode steps of one stream does not
    perturb that stream: its full token sequence (pre- and post-swap
    steps) equals a solo run on the attach-time weights."""
    from kubeml_tpu.serve.engine import SWAP_PATH_VARIANTS, DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    assert "swap_mid_stream" in SWAP_PATH_VARIANTS
    module, v1 = _nano(0)
    _, v2 = _nano(1)

    engine = DecodeEngine(module, v1, slots=2, page=4)
    req = GenerateRequest([5, 6, 7, 8], max_new_tokens=10)
    engine.attach(req)
    _step_until(engine, lambda: len(req.tokens) >= 4)
    pre_swap = list(req.tokens)
    engine.install_weights(v2)
    _drive(engine)

    assert req.outcome == "ok" and len(req.tokens) == 10
    assert req.tokens[:len(pre_swap)] == pre_swap
    np.testing.assert_array_equal(
        req.tokens, _solo_tokens(module, v1, [5, 6, 7, 8], 10,
                                 slots=2, page=4))


def test_swap_cache_partition_no_cross_generation_prefix_hits():
    """The prefix cache is partitioned by weight generation: KV pages
    cached under the old weights are NEVER served to a post-swap
    stream, even for an identical prompt (same-generation sharing keeps
    working)."""
    from kubeml_tpu.serve.engine import SWAP_PATH_VARIANTS, DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    assert "swap_cache_partition" in SWAP_PATH_VARIANTS
    module, v1 = _nano(0)
    _, v2 = _nano(1)
    prompt = [5, 6, 7, 8, 9, 10, 11, 12]

    engine = DecodeEngine(module, v1, slots=4, page=4, prefill_chunk=4)
    # a long stream keeps generation 1 pinned across the swap, so its
    # cache partition stays resident (retirement would also drop it)
    hold = GenerateRequest([3], max_new_tokens=32)
    engine.attach(hold)
    r1 = GenerateRequest(list(prompt), max_new_tokens=2)
    engine.attach(r1)
    _step_until(engine, lambda: r1.outcome is not None)
    assert engine.stats["prefix_hits"] == 0

    # same generation, same prompt: the cached prompt pages ARE shared
    r2 = GenerateRequest(list(prompt), max_new_tokens=2)
    engine.attach(r2)
    _step_until(engine, lambda: r2.outcome is not None)
    same_gen_hits = engine.stats["prefix_hits"]
    assert same_gen_hits > 0
    np.testing.assert_array_equal(r1.tokens, r2.tokens)

    engine.install_weights(v2)
    assert engine.active_generations() == [1, 2]
    # post-swap, identical prompt: must MISS the generation-1 pages
    r3 = GenerateRequest(list(prompt), max_new_tokens=2)
    engine.attach(r3)
    _step_until(engine, lambda: r3.outcome is not None)
    assert engine.stats["prefix_hits"] == same_gen_hits
    np.testing.assert_array_equal(
        r3.tokens, _solo_tokens(module, v2, prompt, 2,
                                slots=4, page=4, prefill_chunk=4))
    _drive(engine)
    assert hold.outcome == "ok"
    assert engine.active_generations() == [2]


def test_pager_prefix_partition_and_drop_generation():
    """Allocator-level regression for the cache partition: the same
    chain hash resolves per generation, and drop_generation retires
    exactly its partition — parked pages return to the free list,
    still-referenced ones free on their stream's release."""
    from kubeml_tpu.serve.pager import (PageAllocator, PageGeometry,
                                        chain_hash)

    geom = PageGeometry(slots=2, page=4, pages=8, pages_per_slot=4)
    pager = PageAllocator(geom)
    digest = chain_hash(b"", [7, 8, 9, 10])

    p1 = pager.alloc()
    assert pager.register_prefix(p1, digest, gen=1)
    p2 = pager.alloc()
    assert pager.register_prefix(p2, digest, gen=2)  # same hash, new gen

    assert pager.lookup_prefix(digest, gen=1) == p1
    assert pager.lookup_prefix(digest, gen=2) == p2
    assert pager.lookup_prefix(digest, gen=3) is None
    pager.free([p1])          # drop the lookup ref
    pager.free([p2])

    pager.free([p1])          # last ref: parks in the LRU (registered)
    assert pager.evictable_pages == 1
    free_before = pager.free_pages
    assert pager.drop_generation(1) == 1
    # the parked generation-1 page went straight back to the free list
    assert pager.free_pages == free_before + 1
    assert pager.evictable_pages == 0
    assert pager.lookup_prefix(digest, gen=1) is None
    # generation 2's partition is untouched
    assert pager.lookup_prefix(digest, gen=2) == p2
    pager.free([p2])

    # a still-referenced page survives the drop and frees on release
    assert pager.drop_generation(2) == 1
    assert pager.refcount(p2) == 1      # the stream still holds it
    free_before = pager.free_pages
    pager.free([p2])
    assert pager.free_pages == free_before + 1


def test_swap_drain_free_retires_old_generation():
    """When the last stream pinned to an old generation releases, the
    generation's params drop and its cache partition frees — the pool
    returns to fully-free and only the live generation stays resident;
    an idle engine holds exactly one generation after a swap."""
    from kubeml_tpu.serve.engine import SWAP_PATH_VARIANTS, DecodeEngine
    from kubeml_tpu.serve.slots import GenerateRequest

    assert "swap_drain_free" in SWAP_PATH_VARIANTS
    module, v1 = _nano(0)
    _, v2 = _nano(1)
    _, v3 = _nano(2)

    engine = DecodeEngine(module, v1, slots=2, page=4)
    req = GenerateRequest([5, 6, 7, 8], max_new_tokens=6)
    engine.attach(req)
    _step_until(engine, lambda: len(req.tokens) >= 1)
    engine.install_weights(v2)
    assert engine.active_generations() == [1, 2]
    assert engine.stats["generations_retired"] == 0

    _drive(engine)
    assert req.outcome == "ok"
    # last generation-1 reader detached: params + cache partition freed
    assert engine.active_generations() == [2]
    assert engine.stats["generations_retired"] == 1
    assert engine.pager.evictable_pages == 0
    assert engine.pager.in_use == 0
    assert engine.pager.free_pages == engine.geom.pages - 1

    # idle swap: the superseded generation retires immediately
    engine.install_weights(v3)
    assert engine.active_generations() == [3]
    assert engine.stats["generations_retired"] == 2


def test_service_hot_swap_e2e_zero_shed():
    """The serving loop across TWO hot-swaps: every stream finishes ok
    (zero shed, zero errors), each decodes bit-identically to a solo
    engine on the generation it was admitted under, and the snapshot's
    weight-generation telemetry lands on the final generation."""
    from kubeml_tpu.serve.engine import DecodeEngine
    from kubeml_tpu.serve.service import ServeService

    module, v1 = _nano(0)
    _, v2 = _nano(1)
    _, v3 = _nano(2)
    engine = DecodeEngine(module, v1, slots=4, page=4)
    svc = ServeService("m1", engine, max_queue=8).start()
    try:
        a = svc.submit([5, 6, 7], max_new_tokens=48)
        deadline = time.time() + 60
        while a.first_token_at is None and time.time() < deadline:
            time.sleep(0.005)
        assert a.first_token_at is not None

        svc.install_weights(v2, stamp=2.0)
        b = svc.submit([9, 10, 11], max_new_tokens=8)
        assert b.wait(60) and b.outcome == "ok"

        svc.install_weights(v3, stamp=3.0)
        c = svc.submit([4, 5], max_new_tokens=8)
        assert c.wait(60) and c.outcome == "ok"
        assert a.wait(60) and a.outcome == "ok"

        assert svc.rejected_total == 0          # nothing shed
        np.testing.assert_array_equal(
            a.tokens, _solo_tokens(module, v1, [5, 6, 7], 48,
                                   slots=4, page=4))
        np.testing.assert_array_equal(
            b.tokens, _solo_tokens(module, v2, [9, 10, 11], 8,
                                   slots=4, page=4))
        np.testing.assert_array_equal(
            c.tokens, _solo_tokens(module, v3, [4, 5], 8,
                                   slots=4, page=4))

        assert engine.stats["weight_swaps"] == 2
        assert engine.stats["compiles"] == 1    # swaps are data
        assert svc.weight_stamp == 3.0
        snap = svc.snapshot()
        assert snap["serve_weight_generation"] == 3
        assert snap["serve_active_generations"] == 1
        assert engine.active_generations() == [3]
    finally:
        svc.stop()


def test_ps_checkpoint_stamp_triggers_hot_swap(tmp_home, mesh8):
    """control/ps._serve_service: a changed checkpoint saved_at stamp
    installs the new weights into the LIVE fleet (generation bumps,
    same engine object in the same replica) instead of rebuilding."""
    import jax

    from kubeml_tpu.control.ps import ParameterServer
    from kubeml_tpu.train.checkpoint import save_checkpoint

    model = get_builtin("gpt-nano")()
    module = model.module
    v1 = model.init_variables(
        jax.random.PRNGKey(0),
        {"x": np.ones((1, module.max_len), np.int32)})
    v2 = model.init_variables(
        jax.random.PRNGKey(1),
        {"x": np.ones((1, module.max_len), np.int32)})
    manifest = {"model": "gpt-nano", "function": "gpt-nano", "epoch": 1}

    ps = ParameterServer(mesh=mesh8, port=0)
    try:
        save_checkpoint("swapjob1", v1, dict(manifest))
        fleet1 = ps._serve_service("swapjob1")
        (_, engine), = fleet1.engines()          # default: one replica
        assert engine.weight_generation == 1
        # same stamp: same fleet, no swap
        assert ps._serve_service("swapjob1") is fleet1
        assert engine.stats["weight_swaps"] == 0

        time.sleep(0.01)  # saved_at stamps must differ
        save_checkpoint("swapjob1", v2, dict(manifest))
        fleet2 = ps._serve_service("swapjob1")
        assert fleet2 is fleet1                  # live fleet reused
        (_, engine2), = fleet2.engines()
        assert engine2 is engine                 # installed, not rebuilt
        deadline = time.time() + 30
        while engine.stats["weight_swaps"] < 1 \
                and time.time() < deadline:
            time.sleep(0.01)
        assert engine.stats["weight_swaps"] == 1
        assert engine.active_generations() == [2]
    finally:
        ps.stop()


# -------------------------------------------------- publish cadence


def test_publish_every_rounds_saves_mid_epoch(tmp_home, mesh8,
                                              monkeypatch):
    """publish_every_rounds emits round-granular checkpoint saves on
    its own cadence (serving picks them up by stamp), independent of
    checkpoint_every_rounds."""
    import kubeml_tpu.train.job as job_mod

    reg = DatasetRegistry()
    _blobs(reg)  # 256 samples / 16 subset / W=2, k=1, b=16 -> 8 rounds
    saves = []

    job = _continual_job(reg, mesh8, "ctpub1", epochs=1, publish_every_rounds=2)
    real_save = job._checkpointer.save

    def spy(job_id, variables, manifest):
        saves.append(manifest.get("train_state", {}).get("round"))
        return real_save(job_id, variables, manifest)

    monkeypatch.setattr(job._checkpointer, "save", spy)
    job.train()
    # rounds 2/4/6/8 hit the publish cadence (mid-epoch, round cursor
    # in the manifest so a crash also resumes there)
    assert [r for r in saves if r is not None] == [2, 4, 6, 8]


# ------------------------------------------------------------ lint + CLI


def test_check_swap_safety_lint_passes_on_repo():
    """The lint itself, over the real tree: every swap path variant is
    covered by this file's tests."""
    import os

    from kubeml_tpu.serve.engine import SWAP_PATH_VARIANTS
    from tools.check_swap_safety import main, path_variants

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    engine_path = os.path.join(root, "kubeml_tpu", "serve", "engine.py")
    assert tuple(path_variants(engine_path)) == SWAP_PATH_VARIANTS
    assert main(["check_swap_safety.py", root]) == 0


def test_check_swap_safety_lint_selftest(tmp_path):
    """The lint catches an uncovered variant, ignores comment-only
    mentions, and fails loudly when the registry is missing."""
    from tools.check_swap_safety import main, uncovered_variants

    eng_dir = tmp_path / "kubeml_tpu" / "serve"
    eng_dir.mkdir(parents=True)
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    engine = eng_dir / "engine.py"
    engine.write_text(
        'SWAP_PATH_VARIANTS = (\n    "covered_swap",\n'
        '    "naked_swap",\n)\n')
    (tests_dir / "test_ok.py").write_text(
        'import numpy as np\n'
        'def test_covered():\n'
        '    # naked_swap mentioned in a comment only: does not count\n'
        '    variant = "covered_swap"\n'
        '    np.testing.assert_array_equal([1], [1])\n')
    assert uncovered_variants(str(engine), str(tests_dir)) == ["naked_swap"]
    assert main(["lint", str(tmp_path)]) == 1
    (tests_dir / "test_fix.py").write_text(
        'def test_naked(engine):\n'
        '    assert "naked_swap"\n'
        '    assert engine.pager.drop_generation(1) == 0\n')
    assert main(["lint", str(tmp_path)]) == 0
    engine.write_text("SWAP_PATH_VARIANTS = ()\n")
    assert main(["lint", str(tmp_path)]) == 1


def test_top_renders_continual_pane():
    from kubeml_tpu.cli.main import _render_top

    doc = {"id": "job1", "state": "healthy", "reasons": [],
           "latest": {"train_loss": 0.5, "dataset_generation": 4,
                      "data_lag_generations": 1,
                      "serve_weight_generation": 3}}
    out = _render_top(doc)
    assert "continual: trained gen 4" in out
    assert "registry lag 1 gen" in out
    assert "served gen 3" in out
    # non-continual samples (wire default -1, or absent) have no pane
    for latest in ({"train_loss": 0.5},
                   {"train_loss": 0.5, "data_lag_generations": -1}):
        plain = _render_top({"id": "job1", "state": "healthy",
                             "reasons": [], "latest": latest})
        assert "continual:" not in plain


def test_cli_train_continual_flag_validation(tmp_home):
    """The CLI gate: --epochs 0 needs --continual; the continual knobs
    need --continual; --publish-every-rounds needs the kavg engine.
    Every failure exits before any network call."""
    from kubeml_tpu.cli.main import build_parser, cmd_train

    parser = build_parser()
    base = ["--controller", "http://127.0.0.1:1", "train", "-f", "m",
            "-d", "ds", "--lr", "0.1"]
    bad = [
        ["-e", "0"],
        ["-e", "2", "--window-generations", "2"],
        ["-e", "2", "--publish-every-rounds", "4"],
        ["-e", "2", "--continual", "--window-generations", "-1"],
        ["-e", "0", "--continual", "--publish-every-rounds", "4",
         "--engine", "syncdp"],
    ]
    for extra in bad:
        with pytest.raises(SystemExit) as ei:
            cmd_train(parser.parse_args(base + extra))
        assert ei.value.code == 1


def test_cli_dataset_append_subcommand_parses():
    from kubeml_tpu.cli.main import build_parser, cmd_dataset_append

    args = build_parser().parse_args(
        ["dataset", "append", "-n", "blobs", "--traindata", "x.npy",
         "--trainlabels", "y.npy", "--generation", "5",
         "--retention", "3"])
    assert args.fn is cmd_dataset_append
    assert (args.name, args.generation, args.retention) == ("blobs", 5, 3)
