"""Manual (fully-manual shard_map) tensor parallelism — parallel/manual.py.

Pins the round-3 composability matrix: manual Megatron TP equals the
dense forward/grads, trains through the K-avg engine, composes with
sequence parallelism in ONE round (round 2's exclusion), and with the
compressed (sub-f32) merge on fully-manual meshes.

Runs on the 8-virtual-CPU-device mesh (conftest).
"""

import jax
from kubeml_tpu import compat
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from kubeml_tpu.models import get_builtin
from kubeml_tpu.parallel.kavg import KAvgEngine
from kubeml_tpu.parallel.mesh import MODEL_AXIS, SEQ_AXIS, make_mesh


@pytest.fixture(scope="module")
def tp2_mesh():
    return make_mesh(n_data=1, n_model=2, devices=jax.devices()[:2])


def _bert_fixture(dropout=0.0):
    model = get_builtin("bert-tiny")()
    model._module = model.module.clone(dropout=dropout)
    return model


def _tiny_gpt(dropout=0.0):
    from tests.test_models_gpt import TinyGPT
    model = TinyGPT()
    model._module = model.module.clone(dropout=dropout)
    return model


def _manual_forward(model, variables, x, mesh):
    """Dense-variables forward through the manual-TP module inside a
    fully-manual shard_map (explicit psums make the output replicated)."""
    tp_module = model.module.clone(tp_axis=MODEL_AXIS)

    def fwd(v, x):
        return tp_module.apply(v, x, train=False)

    return jax.jit(compat.shard_map(
        fwd, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=False))(variables, x)


@pytest.mark.parametrize("dtype,rtol,atol", [
    # f32: the TP decomposition is mathematically exact (pins the
    # collective placement); bf16: production dtype, rounding-order noise
    (jnp.float32, 1e-5, 1e-5),
    (jnp.bfloat16, 5e-2, 2e-2),
])
def test_bert_manual_tp_forward_matches_dense(tp2_mesh, dtype, rtol, atol):
    model = _bert_fixture()
    model._module = model.module.clone(dtype=dtype)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(1, 1000, size=(4, 16)).astype(np.int32))
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x})
    ref = model.module.apply(variables, x, train=False)
    out = _manual_forward(model, variables, x, tp2_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=rtol, atol=atol)


@pytest.mark.parametrize("dtype,rtol,atol", [
    (jnp.float32, 1e-5, 1e-5),
    (jnp.bfloat16, 5e-2, 2e-2),
])
def test_gpt_manual_tp_forward_matches_dense(tp2_mesh, dtype, rtol, atol):
    model = _tiny_gpt()
    model._module = model.module.clone(dtype=dtype)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randint(1, 63, size=(2, 16)).astype(np.int32))
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x})
    ref = model.module.apply(variables, x, train=False)
    out = _manual_forward(model, variables, x, tp2_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=rtol, atol=atol)


def test_manual_tp_init_matches_dense_shapes(tp2_mesh):
    """Initializing THROUGH the TP module (a job that starts tensor-
    parallel) yields the same tree paths/shapes as the dense module."""
    model = _bert_fixture()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(1, 1000, size=(2, 8)).astype(np.int32))
    dense_vars = model.init_variables(jax.random.PRNGKey(0), {"x": x})

    tp_model = _bert_fixture()
    tp_model.enable_tensor_parallel()
    # init goes through init_module (the dense clone) exactly like the
    # job's _init_model does
    tp_vars = tp_model.init_variables(jax.random.PRNGKey(0), {"x": x})
    ref_shapes = jax.tree_util.tree_map(lambda a: a.shape, dense_vars)
    tp_shapes = jax.tree_util.tree_map(lambda a: a.shape, tp_vars)
    assert ref_shapes == tp_shapes


def test_manual_tp_grads_match_dense(tp2_mesh):
    """vma tracking assembles the full parameter gradients across model
    lanes (the invariant->varying psums) — grads equal the dense run."""
    model = _bert_fixture()
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randint(1, 1000, size=(4, 16)).astype(np.int32))
    y = jnp.asarray(rng.randint(0, 2, size=(4,)).astype(np.int32))
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x})
    key = jax.random.PRNGKey(3)
    ones = jnp.ones(x.shape[0])

    def scalar(model_, v, x, y):
        per_ex, _ = model_.loss(v, {"x": x, "y": y}, key, ones)
        return per_ex.mean()

    g_ref = jax.grad(lambda v: scalar(model, v, x, y))(variables)

    tp_model = _bert_fixture()
    tp_model._module = tp_model.module.clone(tp_axis=MODEL_AXIS)

    def tp_grads(v, x, y):
        return jax.grad(lambda v: scalar(tp_model, v, x, y))(v)

    g_tp = jax.jit(compat.shard_map(
        tp_grads, mesh=tp2_mesh, in_specs=(P(), P(), P()), out_specs=P(),
        check_vma=True))(variables, x, y)
    for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                    jax.tree_util.tree_leaves(g_tp)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-2, atol=5e-3)


# ----------------------------------------------------- engine integration


def _round_inputs(rng, W, S, B, T, vocab_hi, with_labels):
    x = rng.randint(1, vocab_hi, size=(W, S, B, T)).astype(np.int32)
    batch = {"x": x}
    if with_labels:
        batch["y"] = rng.randint(0, 2, size=(W, S, B)).astype(np.int32)
    masks = dict(sample_mask=np.ones((W, S, B), np.float32),
                 step_mask=np.ones((W, S), np.float32),
                 worker_mask=np.ones(W, np.float32))
    rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)
    return batch, masks, rngs


def _engine_compare(make_model, enable, mesh_kwargs, with_labels=True,
                    vocab_hi=1000, engine_kwargs=None, ref=None):
    """One K-avg round on the parallel mesh vs pure-DP (data=2); returns
    (ref_out, out) after asserting weight/loss/eval parity."""
    rng = np.random.RandomState(0)
    W, S, B, T = 2, 2, 4, 16
    batch, masks, rngs = _round_inputs(rng, W, S, B, T, vocab_hi,
                                       with_labels)

    model0 = make_model()
    variables = model0.init_variables(
        jax.random.PRNGKey(0),
        jax.tree_util.tree_map(lambda a: jnp.asarray(a[0, 0]), batch))

    def run(mesh, model, **kw):
        eng = KAvgEngine(mesh, model.loss, model.metrics,
                         lambda lr, e: optax.sgd(lr), donate=False,
                         **kw)
        jb = jax.tree_util.tree_map(jnp.asarray, batch)
        out, stats = eng.train_round(variables, jb, rngs=rngs, lr=1e-2,
                                     epoch=0, **masks)
        ev = eng.eval_round(out, jb, masks["sample_mask"])
        return out, float(np.asarray(stats.loss_sum).sum()), ev

    if ref is None:
        ref_model = make_model()
        ref = run(make_mesh(n_data=2, devices=jax.devices()[:2]),
                  ref_model)
    ref_out, loss_ref, ev_ref = ref

    par_model = make_model()
    enable(par_model)
    kw = dict(engine_kwargs or {})
    if par_model.seq_batch_dims is not None and \
            mesh_kwargs.get("n_seq", 1) > 1:
        kw["batch_seq_dims"] = par_model.seq_batch_dims
    out, loss_par, ev_par = run(make_mesh(**mesh_kwargs), par_model, **kw)

    for a, b in zip(jax.tree_util.tree_leaves(ref_out),
                    jax.tree_util.tree_leaves(out)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-2, atol=2e-3)
    # thresholds are wider than the SP-only equivalence test's: manual TP
    # SPLITS the bf16 contractions (different rounding order per lane),
    # SP only re-orders the sequence — measured noise here is ~1e-2 on a
    # loss of ~1, pure bf16 (the f32 forward parity test pins exactness)
    assert abs(loss_ref - loss_par) < 2e-2 * max(1.0, abs(loss_ref))
    assert abs(ev_ref["loss"] - ev_par["loss"]) < 2e-2
    assert ev_ref["n"] == ev_par["n"]
    return ref, out


def test_kavg_trains_manual_tp_bert():
    _engine_compare(
        _bert_fixture,
        lambda m: m.enable_tensor_parallel(),
        dict(n_data=2, n_model=2, devices=jax.devices()[:4]),
        engine_kwargs=dict(manual_inner=True))


def test_kavg_trains_tp_sp_combined():
    """Round 2's exclusion, cleared: TP and SP in ONE fully-manual round
    (heads sharded over `model`, KV ring over `seq`)."""

    def enable(m):
        m.enable_tensor_parallel()
        m.enable_seq_parallel("ring")

    _engine_compare(
        _bert_fixture, enable,
        dict(n_data=2, n_model=2, n_seq=2, devices=jax.devices()[:8]),
        engine_kwargs=dict(manual_inner=True))


def test_kavg_trains_tp_sp_combined_gpt():
    def enable(m):
        m.enable_tensor_parallel()
        m.enable_seq_parallel("ring")

    _engine_compare(
        _tiny_gpt, enable,
        dict(n_data=2, n_model=2, n_seq=2, devices=jax.devices()[:8]),
        with_labels=False, vocab_hi=63,
        engine_kwargs=dict(manual_inner=True))


def test_kavg_manual_tp_compressed_merge():
    """merge_dtype composes with the fully-manual round (the sub-f32
    psum miscompile is partial-manual-only): bf16-merged weights track
    the f32 merge within wire precision."""
    rng = np.random.RandomState(0)
    W, S, B, T = 2, 2, 4, 16
    batch, masks, rngs = _round_inputs(rng, W, S, B, T, 1000, True)
    model = _bert_fixture()
    variables = model.init_variables(
        jax.random.PRNGKey(0),
        jax.tree_util.tree_map(lambda a: jnp.asarray(a[0, 0]), batch))

    def run(merge_dtype):
        m = _bert_fixture()
        m.enable_tensor_parallel()
        eng = KAvgEngine(make_mesh(n_data=2, n_model=2,
                                   devices=jax.devices()[:4]),
                         m.loss, m.metrics, lambda lr, e: optax.sgd(lr),
                         donate=False, manual_inner=True,
                         merge_dtype=merge_dtype)
        out, _ = eng.train_round(
            variables, jax.tree_util.tree_map(jnp.asarray, batch),
            rngs=rngs, lr=1e-2, epoch=0, **masks)
        return out

    f32 = run(None)
    bf16 = run(jnp.bfloat16)
    for a, b in zip(jax.tree_util.tree_leaves(f32),
                    jax.tree_util.tree_leaves(bf16)):
        a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
        np.testing.assert_allclose(b, a, rtol=2e-2, atol=2e-2)


def test_kavg_sp_compressed_merge():
    """Round 2 rejected merge compression x SP-training; the fully-manual
    round now carries it."""
    from tests.test_models_gpt import TinyGPT

    rng = np.random.RandomState(0)
    W, S, B, T = 2, 1, 2, 16
    batch, masks, rngs = _round_inputs(rng, W, S, B, T, 63, False)
    model = TinyGPT()
    model._module = model.module.clone(dropout=0.0)
    variables = model.init_variables(
        jax.random.PRNGKey(0),
        jax.tree_util.tree_map(lambda a: jnp.asarray(a[0, 0]), batch))

    m = TinyGPT()
    m._module = m.module.clone(dropout=0.0)
    m.enable_seq_parallel("ring")
    eng = KAvgEngine(make_mesh(n_data=2, n_seq=2,
                               devices=jax.devices()[:4]),
                     m.loss, m.metrics, lambda lr, e: optax.sgd(lr),
                     donate=False, merge_dtype=jnp.bfloat16,
                     batch_seq_dims=m.seq_batch_dims)
    out, _ = eng.train_round(
        variables, jax.tree_util.tree_map(jnp.asarray, batch),
        rngs=rngs, lr=1e-2, epoch=0, **masks)
    for leaf in jax.tree_util.tree_leaves(out):
        assert np.isfinite(np.asarray(leaf, np.float32)).all()


def test_validate_tp_geometry():
    """Pure-python geometry gate (the smoke-tier representative for this
    subsystem — every other test here compiles multi-axis shard_maps)."""
    from kubeml_tpu.parallel.manual import validate_tp_geometry

    validate_tp_geometry(heads=4, ffn=512, n_model=2)
    with pytest.raises(ValueError, match="heads do not divide"):
        validate_tp_geometry(heads=3, ffn=512, n_model=2)
    with pytest.raises(ValueError, match="FFN width"):
        validate_tp_geometry(heads=4, ffn=511, n_model=2)


def test_manual_tp_rejects_indivisible_heads(tp2_mesh):
    """3 heads on a 2-way model axis: readable trace-time error."""
    from kubeml_tpu.models.bert import BertModule

    module = BertModule(hidden=24, heads=3, ffn=48, layers=1,
                        tp_axis=MODEL_AXIS, dropout=0.0)
    x = jnp.ones((2, 8), jnp.int32)

    def fwd(x):
        return module.init(jax.random.PRNGKey(0), x)

    with pytest.raises(ValueError, match="heads do not divide"):
        jax.jit(compat.shard_map(fwd, mesh=tp2_mesh, in_specs=P(),
                              out_specs=P(), check_vma=False))(x)
