"""TRUE multi-process distributed execution (VERDICT r1 item 2).

Round 1 proved the multislice mesh layout on single-process virtual
devices; this spawns 2 REAL OS processes through tools/launch_distributed
(the product launcher), forms a jax.distributed cluster over CPU
devices (2 processes x 4 devices), and runs a K-avg sync round whose
merge psum crosses the process boundary, plus a cluster-wide checkpoint.
The reference's equivalent role: ml/tests/integration.go:14-36 (control
plane across process boundaries without a real cluster).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def dist_run(tmp_path_factory):
    outdir = str(tmp_path_factory.mktemp("distout"))
    env = dict(os.environ)
    # the launcher sets the emulation env for its children; the launcher
    # itself needs no JAX
    proc = subprocess.run(
        [sys.executable, "-m", "tools.launch_distributed",
         "--processes", "2", "--emulate-cpu", "4", "--",
         sys.executable, os.path.join("tests", "helpers",
                                      "dist_worker_main.py"), outdir],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, \
        f"launcher failed:\n{proc.stdout[-4000:]}\n{proc.stderr[-2000:]}"
    return outdir, proc.stdout


def test_two_process_cluster_runs_kavg_round(dist_run):
    outdir, stdout = dist_run
    # both ranks completed the round + checkpoint
    assert "[p0] proc 0 OK" in stdout
    assert "[p1] proc 1 OK" in stdout
    a = np.load(os.path.join(outdir, "avg_p0.npz"))
    b = np.load(os.path.join(outdir, "avg_p1.npz"))
    assert a.files
    # the replicated averaged model is IDENTICAL on both processes (the
    # psum crossed the process boundary and converged)
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k])


def test_two_process_result_matches_single_process(dist_run, mesh8):
    """The cross-process K-avg round computes the same averaged weights
    as the identical round on a single-process 8-device mesh."""
    import jax
    import jax.numpy as jnp

    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.parallel.kavg import KAvgEngine

    outdir, _ = dist_run
    model = get_builtin("mlp")(hidden=16, num_classes=4)
    rng = np.random.RandomState(0)  # same stream as the worker
    W, S, B, D = 8, 2, 4, 8
    x = rng.randn(W, S, B, D).astype(np.float32)
    y = rng.randint(0, 4, size=(W, S, B)).astype(np.int32)
    rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x[0, 0])})
    variables = jax.tree_util.tree_map(np.asarray, variables)
    engine = KAvgEngine(mesh8, model.loss, model.metrics,
                        model.configure_optimizers, donate=False)
    avg, _ = engine.train_round(
        variables, {"x": x, "y": y},
        sample_mask=np.ones((W, S, B), np.float32),
        step_mask=np.ones((W, S), np.float32),
        worker_mask=np.ones(W, np.float32),
        rngs=rngs, lr=0.1, epoch=0)
    ref = [np.asarray(l) for l in jax.tree_util.tree_leaves(avg)]
    got = np.load(os.path.join(outdir, "avg_p0.npz"))
    for i, r in enumerate(ref):
        np.testing.assert_allclose(got[str(i)], r, rtol=1e-5, atol=1e-6)


def test_checkpoint_written_by_coordinator(dist_run):
    outdir, _ = dist_run
    from kubeml_tpu.train.checkpoint import load_checkpoint
    variables, manifest = load_checkpoint(
        "distjob1", root=os.path.join(outdir, "models"))
    assert manifest["model"] == "mlp"
    a = np.load(os.path.join(outdir, "avg_p0.npz"))
    import jax
    for k, leaf in zip(sorted(a.files, key=int),
                       jax.tree_util.tree_leaves(variables)):
        np.testing.assert_array_equal(a[k], np.asarray(leaf))


def test_launcher_argument_validation():
    """The launcher's mode rules: emulation needs no coordinator; real
    multi-host mode requires --coordinator and --process-id; a missing
    command errors."""
    import tools.launch_distributed as ld

    with pytest.raises(SystemExit):
        ld.main(["--processes", "2"])  # no command
    with pytest.raises(SystemExit):
        ld.main(["--processes", "2", "--", "true"])  # real mode, no coord
    with pytest.raises(SystemExit):  # real mode needs --process-id
        ld.main(["--processes", "2", "--coordinator", "h:1", "--",
                 "true"])
    # emulation mode: spawns the command with the cluster env set
    rc = ld.main(["--processes", "2", "--emulate-cpu", "1", "--",
                  sys.executable, "-c",
                  "import os; "
                  "assert os.environ['KUBEML_NUM_PROCESSES'] == '2'; "
                  "assert os.environ['JAX_NUM_CPU_DEVICES'] == '1'; "
                  "assert 'KUBEML_COORDINATOR_ADDRESS' in os.environ"])
    assert rc == 0
