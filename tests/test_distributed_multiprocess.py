"""TRUE multi-process distributed execution (VERDICT r1 item 2).

Round 1 proved the multislice mesh layout on single-process virtual
devices; this spawns 2 REAL OS processes through tools/launch_distributed
(the product launcher), forms a jax.distributed cluster over CPU
devices (2 processes x 4 devices), and runs a K-avg sync round whose
merge psum crosses the process boundary, plus a cluster-wide checkpoint.
The reference's equivalent role: ml/tests/integration.go:14-36 (control
plane across process boundaries without a real cluster).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_workers(helper_script, outdir, timeout):
    """Launch 2 real processes x 4 virtual CPU devices running
    `tests/helpers/<helper_script>` through the product launcher (which
    sets the cluster env for its children; the launcher itself needs no
    JAX)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.launch_distributed",
         "--processes", "2", "--emulate-cpu", "4", "--",
         sys.executable, os.path.join("tests", "helpers", helper_script),
         outdir],
        cwd=REPO, env=dict(os.environ), capture_output=True, text=True,
        timeout=timeout)
    assert proc.returncode == 0, \
        f"launcher failed:\n{proc.stdout[-6000:]}\n{proc.stderr[-3000:]}"
    return outdir, proc.stdout


@pytest.fixture(scope="module")
def dist_run(tmp_path_factory):
    return _run_workers("dist_worker_main.py",
                        str(tmp_path_factory.mktemp("distout")), 420)


def test_two_process_cluster_runs_kavg_round(dist_run):
    outdir, stdout = dist_run
    # both ranks completed the round + checkpoint
    assert "[p0] proc 0 OK" in stdout
    assert "[p1] proc 1 OK" in stdout
    a = np.load(os.path.join(outdir, "avg_p0.npz"))
    b = np.load(os.path.join(outdir, "avg_p1.npz"))
    assert a.files
    # the replicated averaged model is IDENTICAL on both processes (the
    # psum crossed the process boundary and converged)
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k])


def test_two_process_result_matches_single_process(dist_run, mesh8):
    """The cross-process K-avg round computes the same averaged weights
    as the identical round on a single-process 8-device mesh."""
    import jax
    import jax.numpy as jnp

    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.parallel.kavg import KAvgEngine

    outdir, _ = dist_run
    model = get_builtin("mlp")(hidden=16, num_classes=4)
    rng = np.random.RandomState(0)  # same stream as the worker
    W, S, B, D = 8, 2, 4, 8
    x = rng.randn(W, S, B, D).astype(np.float32)
    y = rng.randint(0, 4, size=(W, S, B)).astype(np.int32)
    rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x[0, 0])})
    variables = jax.tree_util.tree_map(np.asarray, variables)
    engine = KAvgEngine(mesh8, model.loss, model.metrics,
                        model.configure_optimizers, donate=False)
    avg, _ = engine.train_round(
        variables, {"x": x, "y": y},
        sample_mask=np.ones((W, S, B), np.float32),
        step_mask=np.ones((W, S), np.float32),
        worker_mask=np.ones(W, np.float32),
        rngs=rngs, lr=0.1, epoch=0)
    ref = [np.asarray(l) for l in jax.tree_util.tree_leaves(avg)]
    got = np.load(os.path.join(outdir, "avg_p0.npz"))
    for i, r in enumerate(ref):
        np.testing.assert_allclose(got[str(i)], r, rtol=1e-5, atol=1e-6)


def test_checkpoint_written_by_coordinator(dist_run):
    outdir, _ = dist_run
    from kubeml_tpu.train.checkpoint import load_checkpoint
    variables, manifest = load_checkpoint(
        "distjob1", root=os.path.join(outdir, "models"))
    assert manifest["model"] == "mlp"
    a = np.load(os.path.join(outdir, "avg_p0.npz"))
    import jax
    for k, leaf in zip(sorted(a.files, key=int),
                       jax.tree_util.tree_leaves(variables)):
        np.testing.assert_array_equal(a[k], np.asarray(leaf))


def test_launcher_argument_validation():
    """The launcher's mode rules: emulation needs no coordinator; real
    multi-host mode requires --coordinator and --process-id; a missing
    command errors."""
    import tools.launch_distributed as ld

    with pytest.raises(SystemExit):
        ld.main(["--processes", "2"])  # no command
    with pytest.raises(SystemExit):
        ld.main(["--processes", "2", "--", "true"])  # real mode, no coord
    with pytest.raises(SystemExit):  # real mode needs --process-id
        ld.main(["--processes", "2", "--coordinator", "h:1", "--",
                 "true"])
    # emulation mode: spawns the command with the cluster env set
    rc = ld.main(["--processes", "2", "--emulate-cpu", "1", "--",
                  sys.executable, "-c",
                  "import os; "
                  "assert os.environ['KUBEML_NUM_PROCESSES'] == '2'; "
                  "assert os.environ['JAX_NUM_CPU_DEVICES'] == '1'; "
                  "assert 'KUBEML_COORDINATOR_ADDRESS' in os.environ"])
    assert rc == 0


# ------------------------------------------------- full TrainJob (round 3)


@pytest.fixture(scope="module")
def dist_job_run(tmp_path_factory):
    """2 real processes drive the FULL TrainJob epoch loop (dynamic N,
    validation, history, checkpoint) — tests/helpers/dist_job_main.py."""
    return _run_workers("dist_job_main.py",
                        str(tmp_path_factory.mktemp("distjob")), 1500)


def test_full_job_runs_across_two_processes(dist_job_run):
    import json

    outdir, stdout = dist_job_run
    assert "[p0] jobproc 0 OK" in stdout
    assert "[p1] jobproc 1 OK" in stdout
    with open(os.path.join(outdir, "history_p0.json")) as f:
        h0 = json.load(f)
    with open(os.path.join(outdir, "history_p1.json")) as f:
        h1 = json.load(f)
    # the SPMD job loop is deterministic across ranks: identical
    # histories (replicated metrics read from the same global arrays)
    assert h0 == h1
    assert h0["parallelism"] == [2, 4, 8]
    assert len(h0["train_loss"]) == 3
    # both ranks' final checkpoints hold the same replicated weights
    a = np.load(os.path.join(outdir, "final_p0.npz"))
    b = np.load(os.path.join(outdir, "final_p1.npz"))
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k])


def test_job_survives_rank_death_via_supervisor_restart(tmp_path):
    """Worker-process-death recovery across a REAL 2-process cluster
    with NO human in the loop (VERDICT r4 item 2): rank 1 SIGKILLs
    itself mid-job (after the epoch-1 checkpoint is durable), the
    --fail-fast launcher tears the wounded cluster down, and the
    launcher's SUPERVISOR mode — gated on the job's durable checkpoint
    on every rank, the PS watchdog's eligibility rule — relaunches the
    cluster itself; the restarted incarnation resumes from the
    checkpoint and completes the job with one continuous history, the
    restored pre-crash epoch metrics byte-identical to what the crashed
    run recorded. ONE launch, rc=0: crash, restart, and completion all
    happen inside the supervised run."""
    import json

    outdir = str(tmp_path)
    run = subprocess.run(
        [sys.executable, "-m", "tools.launch_distributed",
         "--processes", "2", "--emulate-cpu", "4", "--fail-fast",
         "--max-restarts", "1", "--restart-job", "distjobc",
         "--checkpoint-root", os.path.join(outdir, "p0", "models"),
         "--checkpoint-root", os.path.join(outdir, "p1", "models"),
         "--", sys.executable,
         os.path.join("tests", "helpers", "dist_job_chaos_main.py"),
         outdir],
        cwd=REPO, env=dict(os.environ), capture_output=True, text=True,
        timeout=1500)
    assert run.returncode == 0, \
        f"supervised run failed:\n{run.stdout[-6000:]}\n" \
        f"{run.stderr[-3000:]}"
    # the crash really happened and the supervisor really restarted
    assert "chaos: SIGKILL self" in run.stdout, run.stdout[-4000:]
    assert "supervisor: cluster died" in run.stderr, run.stderr[-2000:]
    assert "[p0] chaosproc 0 OK" in run.stdout
    assert "[p1] chaosproc 1 OK" in run.stdout

    with open(os.path.join(outdir, "resume_history_p0.json")) as f:
        h0 = json.load(f)
    with open(os.path.join(outdir, "resume_history_p1.json")) as f:
        h1 = json.load(f)
    assert h0 == h1  # SPMD determinism holds across the restart too
    assert h0["parallelism"] == [2, 4, 8]
    assert len(h0["train_loss"]) == 3
    # continuity: epoch 1's restored loss == what the crashed run
    # actually published for epoch 1
    with open(os.path.join(outdir, "crash_metrics_p0.jsonl")) as f:
        crash_epochs = [json.loads(line) for line in f]
    assert len(crash_epochs) == 1  # only epoch 1 completed pre-crash
    assert h0["train_loss"][0] == crash_epochs[0]["train_loss"]
    assert h0["parallelism"][0] == crash_epochs[0]["parallelism"]


def test_supervisor_gives_up_without_checkpoint(tmp_path):
    """Watchdog-parity eligibility: a rank failure BEFORE any durable
    checkpoint must not be restarted (nothing to resume) — the
    supervisor reports the casualty instead of looping."""
    run = subprocess.run(
        [sys.executable, "-m", "tools.launch_distributed",
         "--processes", "1", "--emulate-cpu", "1", "--fail-fast",
         "--max-restarts", "3", "--restart-job", "nosuchjob",
         "--checkpoint-root", str(tmp_path),
         "--", sys.executable, "-c", "raise SystemExit(7)"],
        cwd=REPO, env=dict(os.environ), capture_output=True, text=True,
        timeout=120)
    assert run.returncode == 7
    assert "no durable checkpoint" in run.stderr
    assert "relaunching" not in run.stderr


def test_full_job_matches_single_process(dist_job_run, tmp_home):
    """The cross-process job computes the same history as the identical
    job on a single-process 8-device mesh (same data, same scripted
    parallelism schedule)."""
    import json

    from kubeml_tpu.data.registry import DatasetRegistry
    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.parallel.mesh import make_mesh
    from kubeml_tpu.train.history import HistoryStore
    from kubeml_tpu.train.job import JobCallbacks, TrainJob
    from tests.test_job import ToyDataset, make_blobs, make_task

    outdir, _ = dist_job_run
    reg = DatasetRegistry()
    make_blobs(reg)
    store = HistoryStore()
    model = get_builtin("mlp")(hidden=16, num_classes=4)
    schedule = iter([4, 8, 8])
    task = make_task(job_id="distjob2", epochs=3, parallelism=2, k=2,
                     batch=32, lr=0.1, static=False, validate_every=1)
    job = TrainJob(task, model, ToyDataset(), make_mesh(n_data=8),
                   registry=reg, history_store=store,
                   callbacks=JobCallbacks(
                       request_parallelism=lambda t: next(schedule, None)))
    record = job.train()

    with open(os.path.join(outdir, "history_p0.json")) as f:
        h0 = json.load(f)
    assert record.data.parallelism == h0["parallelism"]
    np.testing.assert_allclose(record.data.train_loss, h0["train_loss"],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(record.data.accuracy, h0["accuracy"],
                               rtol=1e-4, atol=1e-4)


def test_two_crashes_two_supervised_restarts(tmp_path):
    """CHAINED recovery with no human in the loop: rank 1 SIGKILLs
    itself in the first incarnation AND again in the supervisor's first
    restart — each crash only after one epoch of NEW durable checkpoint
    progress — and the second restart completes the 4-epoch job with
    one continuous history. The multi-process analogue of
    test_standalone_jobs.py::test_two_crashes_two_restarts_continuous_history."""
    import json

    outdir = str(tmp_path)
    run = subprocess.run(
        [sys.executable, "-m", "tools.launch_distributed",
         "--processes", "2", "--emulate-cpu", "4", "--fail-fast",
         "--max-restarts", "2", "--restart-job", "distjobc",
         "--checkpoint-root", os.path.join(outdir, "p0", "models"),
         "--checkpoint-root", os.path.join(outdir, "p1", "models"),
         "--", sys.executable,
         os.path.join("tests", "helpers", "dist_job_chaos_main.py"),
         outdir],
        cwd=REPO,
        env=dict(os.environ, CHAOS_CRASHES="2", CHAOS_EPOCHS="4"),
        capture_output=True, text=True, timeout=2400)
    assert run.returncode == 0, \
        f"chained supervised run failed:\n{run.stdout[-6000:]}\n" \
        f"{run.stderr[-3000:]}"
    assert run.stdout.count("chaos: SIGKILL self") == 2, \
        run.stdout[-4000:]
    assert run.stderr.count("supervisor: cluster died") == 2, \
        run.stderr[-2000:]
    assert "[p0] chaosproc 0 OK" in run.stdout

    with open(os.path.join(outdir, "resume_history_p0.json")) as f:
        h0 = json.load(f)
    with open(os.path.join(outdir, "resume_history_p1.json")) as f:
        h1 = json.load(f)
    assert h0 == h1
    assert h0["parallelism"] == [2, 4, 8, 8]
    assert len(h0["train_loss"]) == 4
    # continuity across BOTH crashes: epoch 1 published by incarnation
    # 0, epoch 2 by incarnation 1 — the final history restores both
    with open(os.path.join(outdir, "crash_metrics_p0.jsonl")) as f:
        crash_epochs = [json.loads(line) for line in f]
    assert [c["parallelism"] for c in crash_epochs] == [2, 4]
    assert h0["train_loss"][0] == crash_epochs[0]["train_loss"]
    assert h0["train_loss"][1] == crash_epochs[1]["train_loss"]
