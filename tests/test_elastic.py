"""Elastic degraded-mode training (docs/architecture.md): round-granular
resume, mid-epoch work reassignment, preemption grace, heartbeat liveness.

Everything here is coordinate-driven in the FaultPlan sense
(kubeml_tpu/faults.py): preemptions fire at named rounds, crashes are a
hook raising at an exact round, and the liveness reaper is tested as a
pure function of an injected clock. tools/check_fault_tests.py holds
this file to the strict preempt rule — no wall-clock pacing at all.
"""

import json
from types import SimpleNamespace

import jax
import numpy as np
import pytest

from kubeml_tpu.api.errors import JobNotFoundError, JobPreemptedError
from kubeml_tpu.data.loader import RoundLoader
from kubeml_tpu.data.registry import DatasetRegistry
from kubeml_tpu.models import get_builtin
from kubeml_tpu.train.checkpoint import load_checkpoint, save_checkpoint
from kubeml_tpu.train.history import HistoryStore
from kubeml_tpu.train.job import TrainJob

from tests.test_job import ToyDataset, make_task

pytestmark = pytest.mark.elastic

# blobs(256) stored at subset_size=16 -> 16 docs; at parallelism=2,
# k=1, batch=16 each round deals one doc (one 16-sample step) per
# worker -> exactly 8 rounds per epoch, cheap enough to sweep a crash
# through every round
N_TRAIN = 256
SUBSET = 16
NUM_ROUNDS = 8


def _make_small_blobs(reg, n_train=N_TRAIN, n_test=64, dim=8, classes=4,
                      seed=0, subset=SUBSET):
    """tests.test_job.make_blobs with a small storage subset: rounds are
    doc-granular, so the fine subset is what buys a deep round count
    from a tiny (fast) dataset."""
    rng = np.random.RandomState(seed)

    def split(n):
        y = rng.randint(0, classes, n).astype(np.int32)
        x = rng.randn(n, dim).astype(np.float32) * 2.0
        x[np.arange(n), y % dim] += 3.0
        return x, y

    xtr, ytr = split(n_train)
    xte, yte = split(n_test)
    return reg.create("blobs", xtr, ytr, xte, yte, subset_size=subset)


class EmulatedCrash(Exception):
    """Stands in for SIGKILL: raised from the round hook, it unwinds
    train() through the generic failure path (state 'failed', async
    saves drained by the finally), exactly like a process death after
    the same round — but in-process, so one test can sweep it."""


@pytest.fixture()
def jobenv(tmp_home, mesh8):
    reg = DatasetRegistry()
    _make_small_blobs(reg)
    return reg, HistoryStore(), mesh8


def _make_job(jobenv, job_id, *, epochs=2, parallelism=2, k=1, batch=16,
              lr=0.1, resume=False, round_hook=None, **optkw):
    reg, store, mesh = jobenv
    # goal 200: accuracy can never early-stop a run mid-sweep
    task = make_task(job_id=job_id, epochs=epochs, parallelism=parallelism,
                     k=k, batch=batch, lr=lr, goal=200.0)
    for key, val in optkw.items():
        setattr(task.parameters.options, key, val)
    if resume:
        task.parameters.resume_from = job_id
    model = get_builtin("mlp")(hidden=16, num_classes=4)
    return TrainJob(task, model, ToyDataset(), mesh, registry=reg,
                    history_store=store, round_hook=round_hook)


def _weights(job_id):
    variables, manifest = load_checkpoint(job_id)
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(variables)], \
        manifest


def _assert_same_weights(job_a, job_b):
    a, _ = _weights(job_a)
    b, _ = _weights(job_b)
    assert len(a) == len(b)
    for la, lb in zip(a, b):
        np.testing.assert_array_equal(la, lb)


# ------------------------------------------------- preemption grace


def test_preempt_fault_drains_and_resumes_bit_identical(jobenv):
    """A `preempt` fault at (epoch 0, round 3) finishes that round,
    writes a round-granular checkpoint (cursor = 4) and raises
    JobPreemptedError; the restarted incarnation (resume_from = own id,
    which also suppresses the plan's preempt via is_restart) resumes at
    round 4 and finishes with weights bit-identical to a clean run."""
    clean = _make_job(jobenv, "elpclean")
    clean.train()

    plan = json.dumps([{"kind": "preempt", "epoch": 0, "round": 3}])
    job = _make_job(jobenv, "elpre", fault_plan=plan,
                    checkpoint_every_rounds=2)
    with pytest.raises(JobPreemptedError) as ei:
        job.train()
    assert job.task.state == "preempted"
    assert (ei.value.epoch, ei.value.round) == (0, 4)

    _, manifest = _weights("elpre")
    ts = manifest["train_state"]
    assert (ts["epoch"], ts["round"]) == (0, 4)
    assert len(ts["step_counts"]) >= 2  # host accumulators travel along

    resumed = _make_job(jobenv, "elpre", resume=True, fault_plan=plan,
                        checkpoint_every_rounds=2)
    record = resumed.train()
    assert resumed.task.state == "finished"
    # one continuous history across the preemption
    assert len(record.data.train_loss) == 2
    _assert_same_weights("elpre", "elpclean")


def test_epoch_boundary_preempt_checkpoints_next_epoch(jobenv):
    """A preempt request that lands with no round left in the epoch
    (pin on the final round) must still checkpoint and report a valid
    cursor — the NEXT epoch at round 0."""
    plan = json.dumps([{"kind": "preempt", "epoch": 0,
                       "round": NUM_ROUNDS - 1}])
    job = _make_job(jobenv, "elpedge", fault_plan=plan)
    with pytest.raises(JobPreemptedError) as ei:
        job.train()
    assert (ei.value.epoch, ei.value.round) in ((0, NUM_ROUNDS), (1, 0))
    resumed = _make_job(jobenv, "elpedge", resume=True, fault_plan=plan)
    record = resumed.train()
    assert len(record.data.train_loss) == 2
    assert all(np.isfinite(record.data.train_loss))


def test_allocator_preempt_decision_resumes_bit_identical(jobenv):
    """End-to-end cluster preemption: the real ClusterAllocator picks
    the victim (preempt-cheapest path), the victim drains through the
    PR-4 grace (the FaultPlan `preempt` stands in for the SIGTERM the
    scheduler sends), its lanes seat the high-priority arrival on
    release, and the budget-free requeue re-places and finishes the
    victim with weights bit-identical to an uninterrupted run."""
    from kubeml_tpu.control.cluster import ClusterAllocator

    clean = _make_job(jobenv, "elgclean")
    clean.train()

    t = [0.0]
    alloc = ClusterAllocator(2, clock=lambda: t[0], aging_s=0.0)
    (d,) = [d for d in alloc.submit("elgvic", lanes=2) if d.action == "place"]
    assert d.lanes == 2
    t[0] = 1.0
    ds = alloc.submit("elghi", priority=2, lanes=2)
    (p,) = [d for d in ds if d.action == "preempt"]
    assert p.victim == "elgvic"
    assert p.path == "preempt-cheapest"
    assert alloc.preemptions == 1

    # the scheduler SIGTERMs the victim; in-process that is the
    # `preempt` fault -> drain the in-flight round, checkpoint, raise
    plan = json.dumps([{"kind": "preempt", "epoch": 0, "round": 3}])
    victim = _make_job(jobenv, "elgvic", fault_plan=plan,
                       checkpoint_every_rounds=2)
    with pytest.raises(JobPreemptedError):
        victim.train()
    assert victim.task.state == "preempted"
    _, manifest = _weights("elgvic")
    assert (manifest["train_state"]["epoch"],
            manifest["train_state"]["round"]) == (0, 4)

    # the drained victim exits -> its lanes seat the arrival whole
    t[0] = 2.0
    (d,) = [d for d in alloc.release("elgvic") if d.action == "place"]
    assert (d.job_id, d.lanes) == ("elghi", 2)
    hi = _make_job(jobenv, "elghi")
    hi.train()
    assert hi.task.state == "finished"

    # requeue (resume_from = own id): re-admitted, re-placed, and the
    # restart budget is untouched — preemption is not a crash
    t[0] = 3.0
    alloc.release("elghi")
    (d,) = [d for d in alloc.submit("elgvic", lanes=2)
            if d.action == "place"]
    assert d.path == "gang-atomicity"
    resumed = _make_job(jobenv, "elgvic", resume=True, fault_plan=plan,
                        checkpoint_every_rounds=2)
    record = resumed.train()
    assert resumed.task.state == "finished"
    assert resumed.task.restarts == 0
    assert len(record.data.train_loss) == 2
    _assert_same_weights("elgvic", "elgclean")
    snap = alloc.snapshot()
    assert snap["cluster_preemptions_total"] == 1
    assert snap["cluster_gang_placements_total"] == 3


def test_crash_during_preemption_drain_restarts_cleanly(jobenv):
    """A process death in the middle of the preemption drain (preempt
    event set, drain checkpoint never written) must leave a plain
    'failed' job that restarts from the last cadence checkpoint and
    finishes bit-identical — the grace path degrades to the ordinary
    crash path, never a wedged 'preempted' state with a stale cursor."""
    clean = _make_job(jobenv, "eldclean", checkpoint_every_rounds=1)
    clean.train()

    plan = json.dumps([{"kind": "preempt", "epoch": 0, "round": 3}])

    def crash_hook(rb):
        # the plan has already fired for this round (plan runs first),
        # so the preempt event is set; dying here models SIGKILL
        # mid-drain, before the cursor checkpoint lands
        if rb.round_index == 3:
            raise EmulatedCrash("died mid-drain")
        return rb

    job = _make_job(jobenv, "eldrain", fault_plan=plan,
                    checkpoint_every_rounds=1, round_hook=crash_hook)
    with pytest.raises(EmulatedCrash):
        job.train()
    assert job.task.state == "failed"
    _, manifest = _weights("eldrain")
    ts = manifest["train_state"]
    # rounds 0..2 saved by the cadence; the drain's round-4 cursor
    # must NOT exist — the crash beat it
    assert (ts["epoch"], ts["round"]) == (0, 3)

    resumed = _make_job(jobenv, "eldrain", resume=True,
                        checkpoint_every_rounds=1)
    record = resumed.train()
    assert resumed.task.state == "finished"
    assert len(record.data.train_loss) == 2
    _assert_same_weights("eldrain", "eldclean")


# -------------------------------------------- round-granular resume


def test_crash_at_every_round_resumes_bit_identical(jobenv):
    """Satellite sweep: with checkpoint_every_rounds=1, kill the job at
    EVERY round of epoch 0 in turn; each restart resumes at exactly the
    failed round and the final weights are bit-identical to an
    uninterrupted run. The crash is a hook raising at the round's
    consumer-side application point — the same unwind a process death
    leaves behind after the previous round's cadence save drained."""
    clean = _make_job(jobenv, "elsclean", checkpoint_every_rounds=1)
    clean.train()

    for r in range(1, NUM_ROUNDS):
        job_id = f"elcrash{r}"
        state = {"fired": False}

        def crash_hook(rb, _r=r, _state=state):
            if not _state["fired"] and rb.round_index == _r:
                _state["fired"] = True
                raise EmulatedCrash(f"round {_r}")
            return rb

        job = _make_job(jobenv, job_id, checkpoint_every_rounds=1,
                        round_hook=crash_hook)
        with pytest.raises(EmulatedCrash):
            job.train()
        assert job.task.state == "failed"
        _, manifest = _weights(job_id)
        ts = manifest["train_state"]
        # deterministic cursor: rounds 0..r-1 dispatched and saved
        assert (ts["epoch"], ts["round"]) == (0, r), f"crash at round {r}"

        resumed = _make_job(jobenv, job_id, resume=True,
                            checkpoint_every_rounds=1)
        record = resumed.train()
        assert len(record.data.train_loss) == 2
        _assert_same_weights(job_id, "elsclean")


def test_resume_survives_buffer_donation(tmp_home, mesh8):
    """Regression: load_checkpoint hands back host numpy buffers, and
    the engines donate the variables argument on every round — if the
    resume path hands those numpy leaves straight to the first jitted
    dispatch, XLA on the CPU backend may alias and then consume memory
    the host still owns, silently corrupting the warm-started weights.

    The aliasing is allocator-dependent, so this needs a geometry
    observed to trigger it (multi-step rounds over a larger slab;
    the 256-sample fixture above never fires) and a handful of trials:
    on the unfixed resume path this failed 4 of 6 runs, on the fixed
    path every trial is bit-identical by construction."""
    reg = DatasetRegistry()
    # 16 docs of 64 samples -> 8 four-step rounds per epoch
    _make_small_blobs(reg, n_train=1024, subset=64)
    env = (reg, HistoryStore(), mesh8)

    clean = _make_job(env, "eldclean", epochs=3, lr=0.05,
                      checkpoint_every_rounds=2)
    clean.train()

    for t in range(4):
        job_id = f"eldon{t}"
        state = {"seen": 0}

        def crash_hook(rb, _state=state):
            # second visit to round 5 == epoch 1: the resumed job
            # warm-starts from a mid-training cadence checkpoint
            if rb.round_index == 5:
                _state["seen"] += 1
                if _state["seen"] == 2:
                    raise EmulatedCrash()
            return rb

        job = _make_job(env, job_id, epochs=3, lr=0.05,
                        checkpoint_every_rounds=2, round_hook=crash_hook)
        with pytest.raises(EmulatedCrash):
            job.train()
        resumed = _make_job(env, job_id, epochs=3, lr=0.05, resume=True,
                            checkpoint_every_rounds=2)
        resumed.train()
        _assert_same_weights(job_id, "eldclean")


def test_membership_change_discards_round_cursor(jobenv):
    """A round cursor recorded under a different worker count must be
    discarded (the accumulators no longer line up with this epoch's
    rounds): the job replays the epoch from round 0 and completes."""
    job = _make_job(jobenv, "elstale", epochs=1)
    job.train()
    variables, _ = load_checkpoint("elstale")
    save_checkpoint("elstale", variables, {
        "model": "mlp", "function": "mlp", "parallelism": 2, "epoch": 0,
        "train_state": {"epoch": 0, "round": 3,
                        "step_counts": [1.0] * 5,  # wrong membership
                        "loss_sums": [0.0] * 5, "dropped": 0.0,
                        "all_dropped_rounds": 0, "reassigned": 0}})
    resumed = _make_job(jobenv, "elstale", epochs=1, resume=True)
    record = resumed.train()
    assert resumed.task.state == "finished"
    assert len(record.data.train_loss) == 1
    assert np.isfinite(record.data.train_loss[0])


# --------------------------------------- mid-epoch work reassignment


def test_makeup_rounds_cover_orphans_exactly_once(tmp_home):
    """Loader-level exact-once: the planned rounds minus the quarantined
    worker's undispatched chunks, plus the makeup rounds, cover every
    dataset index exactly once."""
    reg = DatasetRegistry()
    handle = _make_small_blobs(reg)
    loader = RoundLoader(handle, ToyDataset(), n_lanes=1)
    plan = loader.plan(4, 1, 16)  # 4 workers x 16/round -> 4 rounds
    q_since = {1: 2}  # worker 1 masked from round 2 on

    seen = np.zeros(N_TRAIN, np.int64)
    for rb in loader.epoch_index_rounds(plan, 0):
        for w in range(4):
            if w == 1 and rb.round_index >= q_since[1]:
                continue  # the guard masks it out pre-dispatch
            ids = rb.batch["idx"][w][rb.sample_mask[w] > 0]
            np.add.at(seen, ids, 1)

    makeups = list(loader.makeup_rounds(plan, 0, q_since, index_mode=True))
    assert makeups, "a mid-epoch quarantine must orphan samples"
    assert makeups[0].round_index == len(plan.rounds)  # appended after
    for rb in makeups:
        assert rb.worker_mask[1] == 0.0  # never re-dealt to the culprit
        for w in range(4):
            ids = rb.batch["idx"][w][rb.sample_mask[w] > 0]
            np.add.at(seen, ids, 1)
    np.testing.assert_array_equal(seen, np.ones(N_TRAIN, np.int64))


def test_job_reassigns_quarantined_workers_rounds(jobenv):
    """Job-level exact-once: a `quarantine` fault on worker 1 at round 4
    re-deals its remaining 4 rounds to the survivor as makeup rounds;
    the hook-observed coverage trains every index exactly once and the
    re-dealt batch count lands in the history."""
    q_round = 4
    captured = []

    def capture(rb):
        captured.append((rb.round_index,
                         np.asarray(rb.batch["idx"]).copy(),
                         np.asarray(rb.sample_mask).copy()))
        return rb

    plan = json.dumps([{"kind": "quarantine", "epoch": 0,
                        "round": q_round, "worker": 1}])
    job = _make_job(jobenv, "elreassign", epochs=1, fault_plan=plan,
                    round_hook=capture, quarantine_after=1,
                    reassign_on_quarantine=True, device_cache="on")
    record = job.train()

    # 4 orphaned rounds x 16 samples re-dealt to 1 survivor at 16/round
    assert record.data.quarantined_workers == [1]
    assert record.data.reassigned_batches == [4]
    planned = [c for c in captured if c[0] < NUM_ROUNDS]
    makeup = [c for c in captured if c[0] >= NUM_ROUNDS]
    assert len(planned) == NUM_ROUNDS and len(makeup) == 4

    seen = np.zeros(N_TRAIN, np.int64)
    for rnd, idx, smask in captured:
        for w in range(idx.shape[0]):
            if w == 1 and rnd >= q_round:
                continue  # guard-masked pre-dispatch from round 4 on
            ids = idx[w][smask[w] > 0]
            np.add.at(seen, ids, 1)
    np.testing.assert_array_equal(seen, np.ones(N_TRAIN, np.int64))


# ------------------------------------- async checkpoint coalescing


def test_async_checkpointer_coalesces_backlogged_saves(tmp_path,
                                                       monkeypatch):
    """Latest-wins backlog: while one save is in flight, further saves
    for the same job collapse into a single pending snapshot; each
    superseded one counts in dropped_saves and the newest manifest is
    the one published."""
    import threading

    import kubeml_tpu.train.checkpoint as ckpt

    gate = threading.Event()
    entered = threading.Event()
    real = ckpt.save_checkpoint

    def slow_save(job_id, variables, manifest, root=None):
        entered.set()
        assert gate.wait(timeout=60)
        return real(job_id, variables, manifest, root=root)

    monkeypatch.setattr(ckpt, "save_checkpoint", slow_save)
    cp = ckpt.AsyncCheckpointer(root=str(tmp_path))
    v = {"params": {"w": np.zeros(3, np.float32)}}
    try:
        cp.save("eljob", v, {"model": "mlp", "seq": 1})
        assert entered.wait(timeout=60)  # first save in flight, gated
        cp.save("eljob", v, {"model": "mlp", "seq": 2})  # pending
        cp.save("eljob", v, {"model": "mlp", "seq": 3})  # supersedes 2
        cp.save("eljob", v, {"model": "mlp", "seq": 4})  # supersedes 3
        assert cp.dropped_saves == 2
        gate.set()
        cp.wait()
    finally:
        gate.set()
        cp.close()
    _, manifest = ckpt.load_checkpoint("eljob", root=str(tmp_path))
    assert manifest["seq"] == 4


# --------------------------------------------- heartbeat liveness


def _ps_with_jobs(records):
    """A ParameterServer with hand-planted job records and NO started
    threads — _scan_heartbeats is pure given `now`."""
    from kubeml_tpu.control.ps import ParameterServer, _JobRecord

    ps = ParameterServer(standalone_jobs=True)
    ps.heartbeat_timeout = 60.0
    kills = []
    for job_id, beat, state in records:
        rec = _JobRecord(make_task(job_id=job_id))
        rec.proc = SimpleNamespace(
            pid=4242, kill=lambda j=job_id: kills.append(j))
        rec.task.state = state
        rec.last_heartbeat = beat
        ps.jobs[job_id] = rec
    return ps, kills


def test_heartbeat_reaper_kills_only_stale_running_children():
    now = 1000.0
    ps, kills = _ps_with_jobs([
        ("hbnever", None, "running"),        # never beat: never reaped
        ("hbfresh", now - 10.0, "running"),  # inside the budget
        ("hbstale", now - 60.0, "running"),  # budget exactly exhausted
        ("hbstop", now - 500.0, "stopping"),  # deliberate stop in flight
    ])
    assert ps._scan_heartbeats(now) == ["hbstale"]
    assert kills == ["hbstale"]
    # one kill per silence: the cleared stamp stops repeat kills until
    # the (restarted) child posts a fresh beat
    assert ps.jobs["hbstale"].last_heartbeat is None
    assert ps._scan_heartbeats(now + 1.0) == []
    # liveness restarts at the next beat, and silence reaps again
    ps.jobs["hbstale"].last_heartbeat = now + 1.0
    ps.jobs["hbfresh"].last_heartbeat = now + 30.0
    assert ps._scan_heartbeats(now + 61.0) == ["hbstale"]
    assert "kubeml_ps_wedged_kills_total" in ps.metrics.exposition()


def test_heartbeat_reaper_disabled_by_zero_budget():
    ps, kills = _ps_with_jobs([("hbz", 1.0, "running")])
    ps.heartbeat_timeout = 0.0
    assert ps._scan_heartbeats(1e9) == []
    assert kills == []


def test_ps_heartbeat_and_preempted_handlers():
    """The wire surface the job child posts to: /heartbeat stamps the
    liveness clock + progress cursor, /preempted marks the record for a
    budget-free reschedule, /tasks exposes both counters."""
    ps, _ = _ps_with_jobs([("hbwire", None, "running")])
    rec = ps.jobs["hbwire"]

    ps._h_heartbeat(SimpleNamespace(params={"jobId": "hbwire"},
                                    body={"epoch": 2, "round": 5}))
    assert rec.last_heartbeat is not None
    assert rec.heartbeat_progress == (2, 5)

    ps._h_preempted(SimpleNamespace(params={"jobId": "hbwire"},
                                    body={"epoch": 2, "round": 5}))
    assert rec.preempted and rec.preemptions == 1
    assert rec.restarts == 0  # grace path never touches the budget

    with pytest.raises(JobNotFoundError):
        ps._h_heartbeat(SimpleNamespace(params={"jobId": "ghost"},
                                        body={}))
    with pytest.raises(JobNotFoundError):
        ps._h_preempted(SimpleNamespace(params={"jobId": "ghost"},
                                        body={}))

    tasks = ps._h_tasks(SimpleNamespace(params={}, body=None))
    assert tasks[0]["preemptions"] == 1 and tasks[0]["restarts"] == 0
    expo = ps.metrics.exposition()
    assert "kubeml_ps_preemptions_total" in expo
    assert 'kubeml_job_heartbeat_epoch{jobid="hbwire"} 2' in expo
    assert 'kubeml_job_heartbeat_round{jobid="hbwire"} 5' in expo


# ----------------------------------------------------- lint teeth


def test_preempt_lint_scopes_sleep_to_preempt_tests(tmp_path):
    """The strict rule is per-file: FaultPlan + preempt forbids
    time.sleep; FaultPlan alone does not (backoff tests legitimately
    sleep)."""
    from tools.check_fault_tests import check_file

    bad = tmp_path / "test_preempt_bad.py"
    bad.write_text("import time\n"
                   "from kubeml_tpu.faults import FaultPlan\n"
                   "def test_preempt_grace():\n"
                   "    time.sleep(1.0)\n")
    assert [v[2] for v in check_file(str(bad))] == ["time.sleep("]

    scoped = tmp_path / "test_no_preempt.py"
    scoped.write_text("import time\n"
                      "from kubeml_tpu.faults import FaultPlan\n"
                      "def test_backoff():\n"
                      "    time.sleep(0.1)\n")
    assert check_file(str(scoped)) == []

    # this very file opts in (FaultPlan + preempt in the docstring) and
    # must stay clean
    assert check_file(__file__) == []
