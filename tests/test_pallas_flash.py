"""Flash-attention pallas kernel vs the jnp reference (interpret mode)."""

import jax
from kubeml_tpu import compat
import jax.numpy as jnp
import numpy as np
import pytest

from kubeml_tpu.ops.attention import (composed_bias, multi_head_attention,
                                      padding_bias)
from kubeml_tpu.ops.pallas.flash_attention import flash_attention

B, T, H, D = 2, 64, 2, 16


def _qkv(rng, dtype=np.float32):
    return (jnp.asarray(rng.randn(B, T, H, D).astype(dtype)),
            jnp.asarray(rng.randn(B, T, H, D).astype(dtype)),
            jnp.asarray(rng.randn(B, T, H, D).astype(dtype)))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [16, 32, 64])
def test_flash_matches_reference(causal, block):
    rng = np.random.RandomState(0)
    q, k, v = _qkv(rng)
    pad = np.ones((B, T), np.float32)
    pad[0, 40:] = 0.0
    pad[1, 7:13] = 0.0
    ref = multi_head_attention(q, k, v,
                               composed_bias(jnp.asarray(pad), causal, T))
    out = flash_attention(q, k, v, jnp.asarray(pad), causal,
                          block, block, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_all_pad_rows_finite():
    rng = np.random.RandomState(1)
    q, k, v = _qkv(rng)
    pad = jnp.zeros((B, T))
    out = flash_attention(q, k, v, pad, False, 32, 32, True)
    assert np.isfinite(np.asarray(out)).all()


def test_flash_grads_match_reference():
    rng = np.random.RandomState(2)
    q, k, v = _qkv(rng)
    pad = np.ones((B, T), np.float32)
    pad[0, 50:] = 0.0
    pad = jnp.asarray(pad)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, pad, True, 32, 32, True) ** 2).sum()

    def loss_ref(q, k, v):
        return (multi_head_attention(
            q, k, v, composed_bias(pad, True, T)) ** 2).sum()

    g_f = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_f, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_flash_bf16():
    rng = np.random.RandomState(3)
    q, k, v = _qkv(rng)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    pad = jnp.ones((B, T))
    ref = multi_head_attention(q, k, v, padding_bias(pad))
    out = flash_attention(q, k, v, pad, False, 32, 32, True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=5e-2, atol=5e-2)


def test_flash_grads_all_pad_row_match_reference():
    """An all-pad row (uniform softmax in the forward) must produce the
    reference's gradients, not length-inflated ones — guards the
    separate-(m, l) stats in the backward (lse = m + log l loses log l
    to f32 rounding at NEG_INF scale, giving p = 1 instead of 1/l)."""
    rng = np.random.RandomState(3)
    q, k, v = _qkv(rng)
    pad = np.ones((B, T), np.float32)
    pad[0, :] = 0.0  # row 0 of batch 0: fully masked
    pad = jnp.asarray(pad)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, pad, False, 32, 32, True) ** 2).sum()

    def loss_ref(q, k, v):
        return (multi_head_attention(
            q, k, v, composed_bias(pad, False, T)) ** 2).sum()

    g_f = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_f, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


# ----------------------------------------- flash-backed ring attention


def _ring_flash_case(causal, ragged):
    import numpy as np

    from kubeml_tpu.ops.attention import (composed_bias,
                                          multi_head_attention,
                                          padding_bias)
    from kubeml_tpu.parallel.mesh import make_mesh
    from kubeml_tpu.parallel.ring_attention import ring_self_attention

    rng = np.random.RandomState(7)
    B, T, H, D = 2, 32, 4, 8
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
               for _ in range(3))
    pad = np.ones((B, T), np.float32)
    if ragged:
        pad[0, 20:] = 0.0   # padding ending inside shard 3 (of 4)
        pad[1, 5:9] = 0.0   # interior masked tokens
    mesh = make_mesh(n_data=1, n_seq=4)
    bias = composed_bias(jnp.asarray(pad), causal, T) if causal \
        else padding_bias(jnp.asarray(pad))
    ref = multi_head_attention(q, k, v, bias)
    out = ring_self_attention(q, k, v, jnp.asarray(pad), mesh,
                              causal=causal, use_flash=True,
                              interpret=True)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_flash_matches_full():
    """use_flash: every ring block runs the pallas kernel; equals full
    attention with ragged padding crossing shard boundaries."""
    _ring_flash_case(causal=False, ragged=True)


def test_ring_flash_causal():
    """Causal flash ring: aligned-diagonal kernel mask on the local
    block + whole-block keep/drop per step equals position-based
    causality under the contiguous shard layout."""
    _ring_flash_case(causal=True, ragged=False)


def test_ring_flash_causal_with_padding():
    _ring_flash_case(causal=True, ragged=True)


def _ring_flash_grad_case(causal, ragged):
    """Grads of the flash-backed ring (per-block kernel partials merged
    across ring steps, custom backward ring with global row stats) must
    equal the dense differentiable ring's — the round-3 VERDICT item
    that makes long-context TRAINING use the pallas kernel."""
    import numpy as np

    from kubeml_tpu.parallel.mesh import make_mesh
    from kubeml_tpu.parallel.ring_attention import ring_self_attention

    rng = np.random.RandomState(13)
    B, T, H, D = 2, 32, 4, 8
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
               for _ in range(3))
    pad = np.ones((B, T), np.float32)
    if ragged:
        pad[0, 20:] = 0.0
        pad[1, 5:9] = 0.0
    pad = jnp.asarray(pad)
    mesh = make_mesh(n_data=1, n_seq=4)
    # weighted-sum loss (not plain sum): a nonuniform cotangent
    # exercises the dq/dk/dv paths with distinct per-row signals
    w = jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))

    def loss(use_flash):
        def f(q, k, v):
            out = ring_self_attention(q, k, v, pad, mesh, causal=causal,
                                      use_flash=use_flash, interpret=True)
            return (out * w).sum()
        return f

    g_dense = jax.grad(loss(False), argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss(True), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_dense, g_flash):
        assert np.isfinite(np.asarray(b)).all(), f"d{name} not finite"
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name} mismatch")


def test_ring_flash_grads_match_dense_ring():
    _ring_flash_grad_case(causal=False, ragged=True)


def test_ring_flash_grads_match_dense_ring_causal():
    _ring_flash_grad_case(causal=True, ragged=False)


def test_ring_flash_grads_match_dense_ring_causal_ragged():
    _ring_flash_grad_case(causal=True, ragged=True)


def _sp_flash_training_round_case(seq_impl, make_x):
    """One K-avg SP training round, flash vs reference attention: the
    merged variables and round loss must match to bf16 tolerance. The
    single comparison harness for both SP modes (ring / ulysses)."""
    import numpy as np
    import optax

    from kubeml_tpu.parallel.kavg import KAvgEngine
    from kubeml_tpu.parallel.mesh import make_mesh
    from tests.test_models_gpt import TinyGPT

    rng = np.random.RandomState(3)
    W, S, B, T = 2, 2, 4, 32
    x = make_x(rng, W, S, B, T)
    batch = {"x": jnp.asarray(x)}
    masks = dict(sample_mask=np.ones((W, S, B), np.float32),
                 step_mask=np.ones((W, S), np.float32),
                 worker_mask=np.ones(W, np.float32))
    rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)
    mesh = make_mesh(n_data=2, n_seq=2, devices=jax.devices()[:4])

    model0 = TinyGPT()
    variables = model0.init_variables(jax.random.PRNGKey(0),
                                      {"x": jnp.asarray(x[0, 0])})

    def run(attn_impl):
        model = TinyGPT()
        model.enable_seq_parallel(seq_impl)
        # dropout 0 for determinism; interpret: pallas interpreter on CPU
        model._module = model.module.clone(
            dropout=0.0, attn_impl=attn_impl, flash_interpret=True)
        eng = KAvgEngine(mesh, model.loss, model.metrics,
                         lambda lr, e: optax.sgd(lr), donate=False,
                         batch_seq_dims=model.seq_batch_dims)
        out, stats = eng.train_round(variables, batch, rngs=rngs, lr=1e-2,
                                     epoch=0, **masks)
        return out, float(np.asarray(stats.loss_sum).sum())

    ref, loss_ref = run("reference")
    fl, loss_fl = run("flash")
    assert abs(loss_ref - loss_fl) < 1e-3 * max(1.0, abs(loss_ref))
    for a, b in zip(jax.tree_util.tree_leaves(ref),
                    jax.tree_util.tree_leaves(fl)):
        assert np.isfinite(np.asarray(b)).all()
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-3, atol=5e-4)


def test_ring_flash_training_round_matches_dense():
    """A FULL K-avg sequence-parallel training round with the
    flash-backed ring (attn_impl='flash') produces the same merged
    variables and round loss as the dense ring — long-context TRAINING
    runs the pallas kernel end to end through the engine path."""
    import numpy as np

    from tests.test_models_gpt import VOCAB

    def make_x(rng, W, S, B, T):
        x = rng.randint(1, VOCAB, size=(W, S, B, T)).astype(np.int32)
        x[0, 0, 0, 20:] = 0  # ragged padding crossing the shard boundary
        return x

    _sp_flash_training_round_case("ring", make_x)


def test_ulysses_flash_training_round_matches_reference():
    """Ulysses + flash in the vma-checked engine round: the all-to-all
    re-shards seq->heads and the gathered-heads attention runs the
    pallas kernel (attn_impl='flash'); merged variables and round loss
    must equal the reference-attention round. Pins the kernel's vma
    annotations for the gathered layout — a path that would otherwise
    only surface on TPU hardware."""
    import numpy as np

    from tests.test_models_gpt import VOCAB

    def make_x(rng, W, S, B, T):
        # pad-free ascending runs (ulysses has no per-block pad path to
        # exercise; the ring case carries the ragged-padding coverage)
        start = rng.randint(1, VOCAB - 1, size=(W * S * B, 1))
        return ((start + np.arange(T)[None, :] - 1) % (VOCAB - 1) + 1) \
            .astype(np.int32).reshape(W, S, B, T)

    _sp_flash_training_round_case("ulysses", make_x)


def test_ring_flash_causal_noncontiguous_layout_poisons():
    """A causal flash call whose q_pos/kv_pos violate the contiguous
    shard layout must fail LOUDLY (NaN output), not silently compute
    wrong attention (round-2 advisor finding)."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from kubeml_tpu.parallel.mesh import SEQ_AXIS, make_mesh
    from kubeml_tpu.parallel.ring_attention import ring_attention

    rng = np.random.RandomState(11)
    B, T, H, D = 1, 32, 2, 4
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
               for _ in range(3))
    pad = jnp.ones((B, T), jnp.float32)
    mesh = make_mesh(n_data=1, n_seq=4)
    # a STRIDED (non-contiguous) position layout: shard s holds global
    # positions s, s+4, s+8, ... — legal for the dense path
    pos = jnp.arange(T).reshape(T // 4, 4).T.reshape(-1)

    def body(q, k, v, pos, pad):
        return ring_attention(q, k, v, pos, pos, pad, causal=True,
                              use_flash=True, interpret=True)

    out = jax.jit(compat.shard_map(
        body, mesh=mesh,
        in_specs=(P(None, SEQ_AXIS), P(None, SEQ_AXIS),
                  P(None, SEQ_AXIS), P(SEQ_AXIS), P(None, SEQ_AXIS)),
        out_specs=P(None, SEQ_AXIS), check_vma=False))(q, k, v, pos, pad)
    assert np.isnan(np.asarray(out)).all(), \
        "layout violation must poison the flash output"

    # the contiguous layout stays finite through the same call path
    out2 = jax.jit(compat.shard_map(
        body, mesh=mesh,
        in_specs=(P(None, SEQ_AXIS), P(None, SEQ_AXIS),
                  P(None, SEQ_AXIS), P(SEQ_AXIS), P(None, SEQ_AXIS)),
        out_specs=P(None, SEQ_AXIS), check_vma=False))(
            q, k, v, jnp.arange(T), pad)
    assert np.isfinite(np.asarray(out2)).all()


def test_ring_self_attention_rejects_noncontiguous_at_host():
    """Causal flash layout violations fail AT THE HOST with a typed
    error when positions are known before trace time (round-5, VERDICT
    r4 item 7) — the NaN poison remains only for the raw shard_map body
    (covered above), whose positions are runtime values."""
    import numpy as np

    from kubeml_tpu.parallel.mesh import make_mesh
    from kubeml_tpu.parallel.ring_attention import (RingLayoutError,
                                                    ring_self_attention)

    rng = np.random.RandomState(3)
    B, T, H, D = 1, 32, 2, 4
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
               for _ in range(3))
    pad = jnp.ones((B, T), jnp.float32)
    mesh = make_mesh(n_data=1, n_seq=4)
    strided = np.arange(T).reshape(T // 4, 4).T.reshape(-1)

    with pytest.raises(RingLayoutError, match="contiguous"):
        ring_self_attention(q, k, v, pad, mesh, causal=True,
                            use_flash=True, interpret=True,
                            positions=strided)
    # shape errors are typed too
    with pytest.raises(RingLayoutError, match="global ids"):
        ring_self_attention(q, k, v, pad, mesh, positions=strided[:8])

    # explicit CONTIGUOUS positions pass and equal the default layout
    out = ring_self_attention(q, k, v, pad, mesh, causal=True,
                              use_flash=True, interpret=True,
                              positions=np.arange(T))
    ref = ring_self_attention(q, k, v, pad, mesh, causal=True,
                              use_flash=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref))
    assert np.isfinite(np.asarray(out)).all()

    # a custom layout remains legal on the DENSE ring (positions are
    # consulted exactly there), where causality is layout-independent
    dense = ring_self_attention(q, k, v, pad, mesh, causal=True,
                                positions=strided)
    assert np.isfinite(np.asarray(dense)).all()
