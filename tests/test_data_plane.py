"""Registry / ingest / loader tests."""

import numpy as np
import pytest

from kubeml_tpu.api.errors import (
    DatasetNotFoundError, InvalidFormatError, StorageError)
from kubeml_tpu.data.ingest import ingest_files
from kubeml_tpu.data.loader import RoundLoader
from kubeml_tpu.data.registry import DatasetRegistry
from kubeml_tpu.models.base import KubeDataset


class PlainDataset(KubeDataset):
    dataset = "toy"


def make_toy(registry, n_train=500, n_test=100):
    rng = np.random.RandomState(0)
    return registry.create(
        "toy",
        rng.rand(n_train, 4).astype(np.float32),
        rng.randint(0, 3, n_train).astype(np.int32),
        rng.rand(n_test, 4).astype(np.float32),
        rng.randint(0, 3, n_test).astype(np.int32))


class TestRegistry:
    def test_create_get_list_delete(self, tmp_path):
        reg = DatasetRegistry(str(tmp_path / "ds"))
        h = make_toy(reg)
        assert h.train_samples == 500 and h.test_samples == 100
        assert h.num_train_docs == 8  # ceil(500/64)
        assert [s.name for s in reg.list()] == ["toy"]
        assert reg.list()[0].train_set_size == 500
        reg.delete("toy")
        assert not reg.exists("toy")
        with pytest.raises(DatasetNotFoundError):
            reg.get("toy")

    def test_duplicate_rejected(self, tmp_path):
        reg = DatasetRegistry(str(tmp_path / "ds"))
        make_toy(reg)
        with pytest.raises(StorageError):
            make_toy(reg)

    def test_length_mismatch_rejected(self, tmp_path):
        reg = DatasetRegistry(str(tmp_path / "ds"))
        with pytest.raises(StorageError):
            reg.create("bad", np.zeros((10, 2)), np.zeros(9),
                       np.zeros((4, 2)), np.zeros(4))

    def test_doc_range_matches_id_range_semantics(self, tmp_path):
        reg = DatasetRegistry(str(tmp_path / "ds"))
        h = make_toy(reg)
        x, y = h.doc_range("train", 2, 4)  # docs 2,3 = samples [128, 256)
        full = np.load(tmp_path / "ds" / "toy" / "train_data.npy")
        np.testing.assert_array_equal(x, full[128:256])
        # final short doc: doc 7 = samples [448, 500)
        x, _ = h.doc_range("train", 7, 8)
        assert len(x) == 52


class TestIngest:
    def test_npy_and_pkl(self, tmp_path):
        import pickle
        rng = np.random.RandomState(1)
        files = {}
        for key, arr in (("xtr", rng.rand(100, 3)), ("ytr", rng.randint(0, 2, 100)),
                         ("xte", rng.rand(20, 3)), ("yte", rng.randint(0, 2, 20))):
            p = tmp_path / f"{key}.npy"
            np.save(p, arr)
            files[key] = str(p)
        # y_test via pickle to cover both formats
        ppath = tmp_path / "yte.pkl"
        with open(ppath, "wb") as f:
            pickle.dump(np.load(files["yte"]), f)
        reg = DatasetRegistry(str(tmp_path / "ds"))
        h = ingest_files("mix", files["xtr"], files["ytr"], files["xte"],
                         str(ppath), registry=reg)
        assert h.train_samples == 100 and h.test_samples == 20

    def test_bad_extension(self, tmp_path):
        (tmp_path / "x.csv").write_text("1,2")
        reg = DatasetRegistry(str(tmp_path / "ds"))
        with pytest.raises(InvalidFormatError):
            ingest_files("bad", str(tmp_path / "x.csv"), str(tmp_path / "x.csv"),
                         str(tmp_path / "x.csv"), str(tmp_path / "x.csv"),
                         registry=reg)


class TestRoundLoader:
    def _loader(self, tmp_path, n_lanes=4, **kw):
        reg = DatasetRegistry(str(tmp_path / "ds"))
        h = make_toy(reg)
        return RoundLoader(h, PlainDataset(), n_lanes=n_lanes, **kw)

    def test_every_real_sample_appears_exactly_once(self, tmp_path):
        loader = self._loader(tmp_path)
        plan = loader.plan(n_workers=3, k=2, batch_size=32)
        seen = 0
        for rb in loader.epoch_rounds(plan, epoch=0):
            seen += int(rb.sample_mask.sum())
            # masked slots never exceed allocation
            W, S, B = rb.sample_mask.shape
            assert rb.batch["x"].shape == (W, S, B, 4)
            assert W % 4 == 0
        assert seen == 500

    def test_worker_mask_padding_lanes(self, tmp_path):
        loader = self._loader(tmp_path, n_lanes=4)
        plan = loader.plan(n_workers=3, k=-1, batch_size=32)
        rounds = list(loader.epoch_rounds(plan, epoch=0))
        assert len(rounds) == 1
        assert rounds[0].worker_mask.tolist() == [1, 1, 1, 0]

    def test_round_data_matches_source(self, tmp_path):
        loader = self._loader(tmp_path)
        plan = loader.plan(n_workers=1, k=-1, batch_size=50)
        rb = next(loader.epoch_rounds(plan, epoch=0))
        flat = rb.batch["x"][0].reshape(-1, 4)
        mask = rb.sample_mask[0].reshape(-1).astype(bool)
        src = np.asarray(loader.handle.train_arrays()[0])
        np.testing.assert_array_equal(flat[mask], src)

    def test_eval_batches_cover_test_split(self, tmp_path):
        loader = self._loader(tmp_path)
        batch, sample_mask = loader.eval_batches(n_workers=3, batch_size=16)
        assert sample_mask.sum() == 100
        W = batch["x"].shape[0]
        assert W % 4 == 0

    def test_shuffle_preserves_sample_count(self, tmp_path):
        loader = self._loader(tmp_path, shuffle=True)
        plan = loader.plan(n_workers=2, k=1, batch_size=32)
        seen = sum(int(rb.sample_mask.sum())
                   for rb in loader.epoch_rounds(plan, epoch=0))
        assert seen == 500
        # different epochs -> different doc order
        rb0 = next(loader.epoch_rounds(plan, epoch=0))
        rb1 = next(loader.epoch_rounds(plan, epoch=1))
        assert not np.array_equal(rb0.batch["x"], rb1.batch["x"])
