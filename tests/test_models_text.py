"""LSTM + BERT-tiny: shapes, padding invariance, engine-round learning."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeml_tpu.models import get_builtin
from kubeml_tpu.parallel.kavg import KAvgEngine

VOCAB = 200
T = 16


def make_text_task(rng, n, num_classes):
    """Learnable synthetic text: class c sequences are dominated by token
    ids in the band [10 + c*20, 10 + c*20 + 20)."""
    y = rng.randint(0, num_classes, size=n).astype(np.int32)
    x = rng.randint(10, VOCAB, size=(n, T)).astype(np.int32)
    for i in range(n):
        band = 10 + y[i] * 20
        x[i, :10] = rng.randint(band, band + 20, size=10)
        x[i, 12:] = 0  # pad tail
    return x, y


@pytest.mark.parametrize("name,ncls", [("lstm", 4), ("bert-tiny", 2)])
def test_forward_shapes(name, ncls):
    model = get_builtin(name)()
    model_cls = type(model)
    assert model_cls.num_classes == ncls
    x = jnp.zeros((2, T), jnp.int32).at[:, 0].set(5)
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x})
    logits = model.module.apply(variables, x, train=False)
    assert logits.shape == (2, ncls)
    assert logits.dtype == jnp.float32


def test_bert_padding_invariance():
    """Content at padded positions must not leak into real-token logits:
    perturbing the position embeddings past the pad boundary leaves the
    output unchanged (the additive attention bias + pooled mask work)."""
    import jax.tree_util as jtu

    model = get_builtin("bert-tiny")()
    rng = np.random.RandomState(0)
    x = rng.randint(1, VOCAB, size=(2, T)).astype(np.int32)
    x[:, 8:] = 0  # pad tail
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(x)})
    base = model.module.apply(variables, jnp.asarray(x), train=False)

    # rewrite pos embeddings for padded positions only
    perturbed = jtu.tree_map(lambda v: v, variables)
    pos = np.asarray(perturbed["params"]["pos_embed"]["embedding"]).copy()
    pos[8:] += 100.0
    perturbed["params"]["pos_embed"]["embedding"] = jnp.asarray(pos)
    out = model.module.apply(perturbed, jnp.asarray(x), train=False)
    np.testing.assert_allclose(np.asarray(base), np.asarray(out),
                               rtol=1e-5, atol=1e-5)

    # all-pad rows stay finite (NEG_INF bias, not -inf -> no NaN softmax)
    allpad = model.module.apply(variables, jnp.zeros_like(jnp.asarray(x)),
                                train=False)
    assert np.isfinite(np.asarray(allpad)).all()


def test_bert_max_len_guard():
    model = get_builtin("bert-tiny")()
    x = jnp.ones((1, 8), jnp.int32)
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x})
    too_long = jnp.ones((1, 200), jnp.int32)
    with pytest.raises(ValueError, match="max_len"):
        model.module.apply(variables, too_long, train=False)


@pytest.mark.parametrize("name,lr", [("lstm", 0.01), ("bert-tiny", 1e-3)])
def test_text_model_learns(mesh8, name, lr):
    rng = np.random.RandomState(0)
    model = get_builtin(name)()
    ncls = type(model).num_classes
    W, S, B = 8, 2, 8
    x, y = make_text_task(rng, W * S * B, ncls)
    xs = x.reshape(W, S, B, T)
    ys = y.reshape(W, S, B)
    variables = model.init_variables(
        jax.random.PRNGKey(0), {"x": jnp.asarray(xs[0, 0])})
    engine = KAvgEngine(mesh8, model.loss, model.metrics,
                        model.configure_optimizers, donate=False)
    batch = {"x": jnp.asarray(xs), "y": jnp.asarray(ys)}
    masks = dict(sample_mask=np.ones((W, S, B)), step_mask=np.ones((W, S)),
                 worker_mask=np.ones(W))
    first = last = None
    for _ in range(6):
        rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)
        variables, stats = engine.train_round(
            variables, batch, rngs=rngs, lr=lr, epoch=0, **masks)
        last = stats.loss_sum.sum() / stats.step_count.sum()
        if first is None:
            first = last
    assert last < first, (first, last)
    out = engine.eval_round(variables, batch, masks["sample_mask"])
    assert out["accuracy"] > 1.0 / ncls


def test_bert_seq_parallel_matches_dense():
    """Long-context path: the seq-sharded forward (ring attention +
    position offsets + psum pooling) must equal the dense forward."""
    import jax
    import numpy as np

    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.parallel.mesh import make_mesh

    model = get_builtin("bert-tiny")()
    rng = np.random.RandomState(0)
    B, T = 2, 32  # 8 tokens per shard on a 4-way seq mesh
    x = rng.randint(1, 1000, size=(B, T)).astype(np.int32)
    x[0, 20:] = 0  # ragged padding crossing shard boundaries
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x})

    dense = model.module.apply(variables, x, train=False)
    mesh = make_mesh(n_data=2, n_seq=4)
    sp = model.forward_seq_parallel(variables, x, mesh)
    assert sp.shape == (B, model.num_classes)
    np.testing.assert_allclose(np.asarray(sp), np.asarray(dense),
                               rtol=2e-2, atol=2e-2)


def test_bert_seq_parallel_ulysses_matches_dense():
    """Same contract for the all-to-all strategy: seq axis 2 so BERT-
    tiny's 2 heads divide it; output equals the dense forward."""
    import jax
    import numpy as np

    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.parallel.mesh import make_mesh

    model = get_builtin("bert-tiny")()
    rng = np.random.RandomState(1)
    B, T = 2, 32
    x = rng.randint(1, 1000, size=(B, T)).astype(np.int32)
    x[0, 20:] = 0
    x[1, 5:9] = 0  # interior pads
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x})

    dense = model.module.apply(variables, x, train=False)
    mesh = make_mesh(n_data=4, n_seq=2)
    sp = model.forward_seq_parallel(variables, x, mesh, impl="ulysses")
    assert sp.shape == (B, model.num_classes)
    np.testing.assert_allclose(np.asarray(sp), np.asarray(dense),
                               rtol=2e-2, atol=2e-2)
