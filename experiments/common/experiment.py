"""Experiment driver — grid sweeps over train submissions.

Parity with the reference harness (ml/experiments/common/experiment.py:
122-181): expand a parameter grid into TrainRequests, submit each through
the client SDK, poll until the task leaves the task list, pull the
persisted History, and derive the paper metrics — time-per-epoch,
max accuracy, and time-to-accuracy (TTA) — from the per-epoch arrays.
Results accumulate as plain dict rows; `to_frame` gives a pandas
DataFrame when pandas is present.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import time
from typing import Dict, Iterable, List, Optional

from kubeml_tpu.api.types import History, TrainOptions, TrainRequest
from kubeml_tpu.control.client import KubemlClient


def expand_grid(grid: Dict[str, Iterable]) -> List[Dict]:
    """Cartesian product of a parameter grid, reference-style
    (ml/experiments/common/utils.py:12-28 defines grids as dicts of
    lists)."""
    keys = list(grid)
    return [dict(zip(keys, vals))
            for vals in itertools.product(*(grid[k] for k in keys))]


def time_to_accuracy(history: History, goal_pct: float) -> Optional[float]:
    """Seconds of training until validation accuracy first reaches
    goal_pct, per the reference's TTA methodology (figures tta99/tta70;
    goal-accuracy stop `ml/pkg/train/job.go:354-359`). None if never
    reached."""
    elapsed = 0.0
    accs = history.data.accuracy
    durs = history.data.epoch_duration
    for i, dur in enumerate(durs):
        elapsed += dur
        if i < len(accs) and accs[i] >= goal_pct:
            return elapsed
    return None


@dataclasses.dataclass
class ExperimentResult:
    job_id: str
    config: Dict
    history: History
    wall_time: float

    def row(self, tta_goals: Iterable[float] = ()) -> Dict:
        h = self.history.data
        row = dict(self.config)
        row.update({
            "job_id": self.job_id,
            "wall_time_s": round(self.wall_time, 3),
            "epochs_run": len(h.train_loss),
            "train_time_s": round(sum(h.epoch_duration), 3),
            "mean_epoch_s": (round(sum(h.epoch_duration)
                                   / max(len(h.epoch_duration), 1), 3)),
            "final_train_loss": h.train_loss[-1] if h.train_loss else None,
            "final_accuracy": h.accuracy[-1] if h.accuracy else None,
            "max_accuracy": max(h.accuracy) if h.accuracy else None,
            "final_parallelism": (h.parallelism[-1]
                                  if h.parallelism else None),
            # the full per-epoch trajectory: for dynamic (autoscale)
            # runs the ±1 path the policy actually took is the result,
            # not just its endpoint
            "parallelism_trajectory": list(h.parallelism),
            "epoch_durations_s": [round(d, 4) for d in h.epoch_duration],
        })
        for goal in tta_goals:
            row[f"tta{goal:g}_s"] = time_to_accuracy(self.history, goal)
        return row


class KubemlExperiment:
    """Submit TrainRequests and collect results through the public API."""

    def __init__(self, client: Optional[KubemlClient] = None,
                 poll_interval: float = 0.5, timeout: float = 3600.0):
        self.client = client or KubemlClient()
        self.poll_interval = poll_interval
        self.timeout = timeout
        self.results: List[ExperimentResult] = []

    def make_request(self, function: str, dataset: str, epochs: int,
                     batch: int, lr: float, parallelism: int, k: int,
                     static: bool = True, validate_every: int = 1,
                     goal_accuracy: float = 100.0,
                     shuffle: bool = False,
                     max_parallelism: int = 0) -> TrainRequest:
        return TrainRequest(
            model_type=function, function_name=function, dataset=dataset,
            epochs=epochs, batch_size=batch, lr=lr,
            options=TrainOptions(default_parallelism=parallelism,
                                 static_parallelism=static,
                                 validate_every=validate_every, k=k,
                                 goal_accuracy=goal_accuracy,
                                 shuffle=shuffle,
                                 max_parallelism=max_parallelism))

    def run(self, req: TrainRequest, config: Optional[Dict] = None
            ) -> ExperimentResult:
        """Submit one request and block until its history is persisted."""
        v1 = self.client.v1()
        t0 = time.time()
        job_id = v1.networks().train(req)
        deadline = t0 + self.timeout
        history = None
        while time.time() < deadline:
            running = {t.job_id for t in v1.tasks().list()}
            if job_id not in running:
                try:
                    history = v1.histories().get(job_id)
                    break
                except Exception:
                    pass  # finish raced ahead of the history write
            time.sleep(self.poll_interval)
        if history is None:
            raise TimeoutError(f"job {job_id} did not finish in "
                               f"{self.timeout}s")
        result = ExperimentResult(job_id=job_id,
                                  config=config or self._req_config(req),
                                  history=history,
                                  wall_time=time.time() - t0)
        self.results.append(result)
        return result

    def run_grid(self, function: str, dataset: str, grid: Dict[str, Iterable],
                 epochs: int, lr: float, static: bool = True,
                 on_result=None) -> List[ExperimentResult]:
        """Run the full cartesian grid; grid keys: batch, k, parallelism.
        static=False benchmarks the scheduler's dynamic-parallelism
        autoscale (BASELINE config 3)."""
        out = []
        for cfg in expand_grid(grid):
            req = self.make_request(
                function=function, dataset=dataset, epochs=epochs,
                batch=cfg["batch"], lr=lr, parallelism=cfg["parallelism"],
                k=cfg["k"], static=static)
            full_cfg = {"function": function, "dataset": dataset,
                        "epochs": epochs, "lr": lr, "static": static,
                        **cfg}
            res = self.run(req, config=full_cfg)
            out.append(res)
            if on_result:
                on_result(res)
        return out

    @staticmethod
    def _req_config(req: TrainRequest) -> Dict:
        return {"function": req.function_name or req.model_type,
                "dataset": req.dataset, "epochs": req.epochs,
                "lr": req.lr, "batch": req.batch_size,
                "k": req.options.k,
                "parallelism": req.options.default_parallelism}

    # ------------------------------------------------------------- reporting

    def rows(self, tta_goals: Iterable[float] = ()) -> List[Dict]:
        return [r.row(tta_goals) for r in self.results]

    def to_frame(self, tta_goals: Iterable[float] = ()):
        import pandas as pd
        return pd.DataFrame(self.rows(tta_goals))

    def save_jsonl(self, path: str, tta_goals: Iterable[float] = ()) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            for row in self.rows(tta_goals):
                f.write(json.dumps(row) + "\n")
