"""Published sweep grids — the benchmark protocol of the reference.

These reproduce the parameter grids of ml/experiments/common/utils.py:
12-28 and ml/experiments/train.py:14-61 verbatim, so results are
comparable sweep-for-sweep with the reference figures (BASELINE.md).
"""

# LeNet/MNIST: batch x K x parallelism, lr 0.01, 30 epochs, static
# (ml/experiments/common/utils.py:12-16, train.py:14-38)
LENET_GRID = {
    "batch": [128, 64, 32, 16],
    "k": [-1, 32, 16, 8],
    "parallelism": [1, 2, 4, 8],
}
LENET_EPOCHS = 30
LENET_LR = 0.01
LENET_TTA_GOAL = 99.0  # TTA-99 figure (figures/paper/lenet/tta99.pdf)

# ResNet/CIFAR-10: active grid of utils.py:18-28 (batch sweep, K=-1, p=8),
# lr 0.1, 30 epochs (train.py:41-61). The reference uses ResNet-34; our
# flagship config is ResNet-18 per BASELINE.json's north star, and the
# same grid runs for either depth.
RESNET_GRID = {
    "batch": [256, 128, 64, 32],
    "k": [-1],
    "parallelism": [8],
}
RESNET_EPOCHS = 30
RESNET_LR = 0.1
RESNET_TTA_GOAL = 70.0  # TTA-70 figure (figures/paper/resnet34/tta70.pdf)
