"""Published sweep grids — the benchmark protocol of the reference.

These reproduce the parameter grids of ml/experiments/common/utils.py:
12-28 and ml/experiments/train.py:14-61 verbatim, so results are
comparable sweep-for-sweep with the reference figures (BASELINE.md).
"""

# LeNet/MNIST: batch x K x parallelism, lr 0.01, 30 epochs, static
# (ml/experiments/common/utils.py:12-16, train.py:14-38)
LENET_GRID = {
    "batch": [128, 64, 32, 16],
    "k": [-1, 32, 16, 8],
    "parallelism": [1, 2, 4, 8],
}
LENET_EPOCHS = 30
LENET_LR = 0.01
LENET_TTA_GOAL = 99.0  # TTA-99 figure (figures/paper/lenet/tta99.pdf)

# LeNet on the REAL digits arm (experiments/data.py: the one genuine
# image dataset available without egress). Same protocol shape as the
# reference grid, sized to the 1,437-sample train split: full batch
# sweep, sparse-vs-K=8 averaging, parallelism sweep; TTA goal 95 (the
# 360-sample test split makes 99% a coin flip of 3-4 samples, so the
# derived TTA target is 95% — max accuracy is still recorded per run).
# lr 0.1, not MNIST's 0.01: at ~45 steps/epoch (vs MNIST's ~1900) the
# protocol needs the larger step to converge inside the sweep budget —
# measured 97.4% max accuracy in 10 epochs on the baseline arm vs 44%
# at lr 0.01.
LENET_DIGITS_GRID = {
    "batch": [128, 64, 32, 16],
    "k": [-1, 8],
    "parallelism": [1, 4, 8],
}
LENET_DIGITS_EPOCHS = 15
LENET_DIGITS_LR = 0.1
LENET_DIGITS_TTA_GOAL = 95.0

# Matched-GLOBAL-batch study (round 3): the round-2 sweep compared
# N-worker arms against N=1 at the SAME per-worker batch, which hands
# the parallel arms N x the global batch — exactly the comparison the
# reference's own global-batch-vs-acc figure warns about
# (figures/paper/resnet34/global-batch-vs-acc.pdf: accuracy falls as
# global batch grows). The fair local-SGD claims need coupled
# (batch, parallelism) arms, so this grid is an explicit config LIST:
#   - N=4 x b16 vs N=1 x b64: same sequential step count per epoch —
#     isolates local-SGD data efficiency vs large-batch SGD;
#   - N=4 x b16 vs N=1 x b16: same math per sample — isolates the
#     engine's K-batched dispatch (wall-clock) advantage;
#   - N=8 x b8 extends both axes.
# 30 epochs (not 15): the sweep measures epochs-to-accuracy curves, not
# just whether the fastest arm gets there.
LENET_DIGITS_GBATCH_CONFIGS = [
    {"batch": 64, "k": -1, "parallelism": 1},
    {"batch": 16, "k": -1, "parallelism": 1},
    {"batch": 16, "k": 8, "parallelism": 1},
    {"batch": 16, "k": -1, "parallelism": 4},
    {"batch": 16, "k": 8, "parallelism": 4},
    {"batch": 16, "k": 4, "parallelism": 4},
    {"batch": 8, "k": 8, "parallelism": 8},
    {"batch": 8, "k": -1, "parallelism": 8},
]
LENET_DIGITS_GBATCH_EPOCHS = 30

# REAL-data dynamic-parallelism arm: one config, static=False — the live
# throughput policy drives N between epochs over genuine digit images
# (the real-data sibling of the RESNET50 synthetic autoscale arm).
LENET_DIGITS_AUTOSCALE_GRID = {
    "batch": [32],
    "k": [8],
    "parallelism": [4],
    # the cap doubles as the PINNED round shape (train/job.py elastic
    # shape pinning): N moves only through the worker mask, so the
    # policy's ±1 steps are recompile-free
    "max_parallelism": [8],
}

# ResNet/CIFAR-10: active grid of utils.py:18-28 (batch sweep, K=-1, p=8),
# lr 0.1, 30 epochs (train.py:41-61). The reference uses ResNet-34; our
# flagship config is ResNet-18 per BASELINE.json's north star, and the
# same grid runs for either depth.
RESNET_GRID = {
    "batch": [256, 128, 64, 32],
    "k": [-1],
    "parallelism": [8],
}
RESNET_EPOCHS = 30
RESNET_LR = 0.1
RESNET_TTA_GOAL = 70.0  # TTA-70 figure (figures/paper/resnet34/tta70.pdf)

# --- BASELINE.json configs 3-5 (net-new vs the reference's figures; the
# reference has no published grid for these, so the grids below define the
# framework's benchmark protocol for them) ---

# ResNet-50/Imagenette: scheduler dynamic-parallelism autoscale
# (BASELINE.json config 3) — static=False, the throughput policy resizes
# between epochs (ml/pkg/scheduler/policy.go:50-94 semantics).
RESNET50_GRID = {
    "batch": [128, 64],
    "k": [-1],
    "parallelism": [4],
    # capped autoscale: W pins at 8; k=-1 means S still tracks N, each
    # N's program a one-time persistently-cached compile excluded from
    # the policy's timing (data/loader.py epoch_rounds)
    "max_parallelism": [8],
}
RESNET50_EPOCHS = 30
RESNET50_LR = 0.05
RESNET50_TTA_GOAL = 70.0

# 2-layer LSTM/AG-News: recurrent step under jit (BASELINE.json config 4)
LSTM_GRID = {
    "batch": [64, 32],
    "k": [-1, 8],
    "parallelism": [4],
}
LSTM_EPOCHS = 10
LSTM_LR = 1e-3
LSTM_TTA_GOAL = 85.0

# BERT-tiny/SST-2: ICI all-reduce at K=16 (BASELINE.json config 5)
BERT_GRID = {
    "batch": [32, 16],
    "k": [16],
    "parallelism": [4],
}
BERT_EPOCHS = 5
BERT_LR = 1e-4
BERT_TTA_GOAL = 80.0
