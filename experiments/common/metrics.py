"""System-metric sampling during experiment runs.

Parity with the reference's out-of-band collector
(ml/experiments/common/metrics.py:95-160), which samples psutil/GPUtil
every 2 seconds through a side Flask API. Here the sampler is in-process
(the experiments and training share the TPU host), records CPU, memory,
and this process's RSS, and snapshots results to JSON. GPU sampling is
intentionally absent — accelerator-side behavior is captured by the
per-epoch duration/parallelism arrays in the job History instead.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

try:
    import psutil
except ImportError:  # environment without psutil: sampler becomes a no-op
    psutil = None


class SystemMetricsSampler:
    """Background sampler; start()/stop() around an experiment run."""

    def __init__(self, interval: float = 2.0):
        self.interval = interval
        self.samples: List[Dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._proc = psutil.Process() if psutil else None

    def _sample(self) -> Dict:
        return {
            "ts": time.time(),
            "cpu_pct": psutil.cpu_percent(interval=None),
            "mem_pct": psutil.virtual_memory().percent,
            "proc_rss_mb": self._proc.memory_info().rss / 2**20,
        }

    def _loop(self):
        psutil.cpu_percent(interval=None)  # prime the counter
        while not self._stop.wait(self.interval):
            self.samples.append(self._sample())

    def start(self) -> "SystemMetricsSampler":
        if psutil is None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> List[Dict]:
        if self._thread:
            self._stop.set()
            self._thread.join(timeout=self.interval + 1)
            self._thread = None
        return self.samples

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.samples, f)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
