"""Text/transformer on-chip benchmarks — BASELINE.json configs 4-5 plus
the flash-kernel model-level delta and the KV-cache decode path.

Four measurements, bench.py-grade methodology (synthetic token data on
device, warmup epochs outside the timed window, readback-synchronized
timing — never block_until_ready on tunneled backends, fresh inputs per
iteration so no executable+input cache can serve a repeat):

  lstm   — 2-layer LSTM classifier through the REAL K-avg engine round
           (BASELINE config 4: recurrent lax.scan step under jit).
  bert   — BERT-tiny classifier through the engine round at K=16
           (BASELINE config 5: the merge runs every 16 local steps).
  flash  — model-level flash-vs-reference attention delta: full
           value_and_grad step time for GPT-mini and BERT-tiny geometry
           at long context (default T=2048) with attn_impl='flash' vs
           'reference' — the first hardware quantification of the
           pallas kernel's end-to-end training worth.
  generate — KV-cache decode throughput (models/gpt.py generate):
           prefill once, then the jitted single-token decode scan —
           the inference hot path's tokens/sec.

Usage:
    python -m experiments.bench_text [--which lstm,bert,flash,generate]
        [--out results/text-bench-v5e.jsonl] [--seq 2048]

Appends one JSON row per measurement; prints each row as it lands.
"""

from __future__ import annotations

import argparse
import json
import math
import time


def _sync(x) -> float:
    """Readback-synchronized wait: returns a scalar derived from x."""
    import numpy as np
    return float(np.asarray(x).ravel()[0])


def bench_engine_text(model_name: str, k: int, batch: int, seq_len: int,
                      vocab: int, workers: int, epoch_samples: int,
                      timed_epochs: int = 3) -> dict:
    """Throughput of the real K-avg round path on a text model."""
    import jax
    import numpy as np

    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.parallel.kavg import KAvgEngine
    from kubeml_tpu.parallel.mesh import make_mesh
    from kubeml_tpu.train.job import reduce_losses

    jnp = jax.numpy
    mesh = make_mesh(n_data=len(jax.devices()))
    model = get_builtin(model_name)()

    rng = np.random.RandomState(0)
    W, S, B, T = workers, k, batch, seq_len
    rounds_per_epoch = max(1, math.ceil(epoch_samples / (W * S * B)))
    x = rng.randint(1, vocab, size=(W, S, B, T)).astype(np.int32)
    lengths = rng.randint(T // 4, T + 1, size=(W, S, B))
    x[np.arange(T)[None, None, None, :] >= lengths[..., None]] = 0
    y = rng.randint(0, 2, size=(W, S, B)).astype(np.int32)
    batch_dev = {"x": jnp.asarray(x), "y": jnp.asarray(y)}
    masks = dict(sample_mask=np.ones((W, S, B), np.float32),
                 step_mask=np.ones((W, S), np.float32),
                 worker_mask=np.ones(W, np.float32))
    variables = model.init_variables(
        jax.random.PRNGKey(0), {"x": jnp.asarray(x[0, 0]),
                                "y": jnp.asarray(y[0, 0])})
    engine = KAvgEngine(mesh, model.loss, model.metrics,
                        model.configure_optimizers)

    def epoch(variables, e):
        dev_losses = []
        for _ in range(rounds_per_epoch):
            rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)
            variables, stats = engine.train_round(
                variables, batch_dev, rngs=rngs, lr=1e-3, epoch=e, **masks)
            dev_losses.append(stats.loss_sum_device)
        loss = _sync(reduce_losses(dev_losses))
        return variables, loss

    for w in range(2):  # compile + transfer-path warmup
        variables, _ = epoch(variables, w)
    _sync(jax.tree_util.tree_leaves(variables)[0])

    t0 = time.perf_counter()
    for e in range(timed_epochs):
        variables, _ = epoch(variables, e + 1)
    _sync(jax.tree_util.tree_leaves(variables)[0])
    elapsed = time.perf_counter() - t0

    samples = timed_epochs * rounds_per_epoch * W * S * B
    return {
        "bench": f"{model_name}_engine_throughput",
        "model": model_name, "k": k, "batch": batch, "seq_len": T,
        "workers": W, "rounds_per_epoch": rounds_per_epoch,
        "samples_per_sec_per_chip": round(
            samples / elapsed / len(jax.devices()), 1),
        "tokens_per_sec_per_chip": round(
            samples * T / elapsed / len(jax.devices()), 1),
    }


def bench_flash_delta(family: str, T: int, batch: int,
                      iters: int = 20) -> dict:
    """Model-level flash on/off: full train-step (value_and_grad +
    SGD apply) wall time at long context, one chip."""
    import jax
    import numpy as np
    import optax

    jnp = jax.numpy
    if family == "gpt":
        from kubeml_tpu.models.gpt import GPTModule

        def build(impl):
            return GPTModule(vocab_size=8192, max_len=T, hidden=256,
                             layers=4, heads=4, ffn=1024, dropout=0.0,
                             attn_impl=impl)

        def loss_fn(module, variables, xb, yb):
            logits = module.apply(variables, xb, train=False)
            # causal LM loss over all positions
            tgt = jnp.concatenate([xb[:, 1:], xb[:, :1]], axis=1)
            lp = jax.nn.log_softmax(logits, axis=-1)
            ll = jnp.take_along_axis(lp, tgt[..., None], axis=-1)[..., 0]
            return -(ll.mean())
    elif family == "bert":
        from kubeml_tpu.models.bert import BertModule

        def build(impl):
            return BertModule(vocab_size=8192, max_len=T, hidden=128,
                              layers=2, heads=2, ffn=512, num_classes=2,
                              dropout=0.0, attn_impl=impl)

        def loss_fn(module, variables, xb, yb):
            logits = module.apply(variables, xb, train=False)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean()
    else:
        raise ValueError(family)

    rng = np.random.RandomState(0)
    xb = jnp.asarray(rng.randint(1, 8192, size=(batch, T)).astype(np.int32))
    yb = jnp.asarray(rng.randint(0, 2, size=(batch,)).astype(np.int32))

    def measure(impl):
        module = build(impl)
        variables = module.init(jax.random.PRNGKey(0), xb)
        tx = optax.sgd(1e-3)
        opt_state = tx.init(variables["params"])

        @jax.jit
        def step(variables, opt_state, xb, yb):
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(module, {**variables, "params": p},
                                  xb, yb))(variables["params"])
            updates, opt_state = tx.update(grads, opt_state,
                                           variables["params"])
            params = optax.apply_updates(variables["params"], updates)
            return {**variables, "params": params}, opt_state, loss

        for _ in range(3):  # compile + ramp
            variables, opt_state, loss = step(variables, opt_state, xb, yb)
        _sync(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            variables, opt_state, loss = step(variables, opt_state, xb, yb)
        _sync(loss)
        return (time.perf_counter() - t0) / iters

    ref_s = measure("reference")
    flash_s = measure("flash")
    return {
        "bench": f"{family}_flash_delta", "family": family, "seq_len": T,
        "batch": batch, "reference_step_ms": round(ref_s * 1e3, 3),
        "flash_step_ms": round(flash_s * 1e3, 3),
        "flash_speedup": round(ref_s / flash_s, 3),
        "tokens_per_sec_flash": round(batch * T / flash_s, 1),
    }


def bench_generate(T_prompt: int = 128, n_new: int = 512,
                   batch: int = 8, iters: int = 3) -> dict:
    """KV-cache decode throughput: prefill once, then the jitted
    single-token decode scan (models/gpt.py generate) — the inference
    hot path. Tokens/sec counts GENERATED tokens only; generate()
    returns host arrays, so each call is readback-synchronized by
    construction."""
    import jax
    import numpy as np

    from kubeml_tpu.models.gpt import GPTMini, GPTModule

    class _BenchGPT(GPTMini):
        def build(self):
            return GPTModule(vocab_size=8192, max_len=T_prompt + n_new,
                             hidden=256, layers=4, heads=4, ffn=1024,
                             dropout=0.0)

    jnp = jax.numpy
    model = _BenchGPT()
    rng = np.random.RandomState(0)
    prompts = rng.randint(1, 8192, size=(batch, T_prompt)).astype(np.int32)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(prompts)})

    # fresh prompts per iter (cache-busting), generated OUTSIDE the
    # timed window so host-side randint never lands in the measurement
    fresh = [rng.randint(1, 8192, size=(batch, T_prompt)).astype(np.int32)
             for _ in range(iters)]
    model.generate(variables, prompts, max_new_tokens=n_new)  # compile
    t0 = time.perf_counter()
    for p in fresh:
        out = model.generate(variables, p, max_new_tokens=n_new)
    elapsed = time.perf_counter() - t0
    assert out.shape == (batch, T_prompt + n_new)
    new_tokens = iters * batch * n_new
    return {
        "bench": "gpt_kvcache_decode", "prompt_len": T_prompt,
        "new_tokens": n_new, "batch": batch,
        "decode_tokens_per_sec": round(new_tokens / elapsed, 1),
        # the timed window spans prefill + decode per call; the
        # per-step figure amortizes the (short) prefill over the
        # decode steps — name it accordingly
        "ms_per_generated_token": round(
            elapsed / (iters * n_new) * 1e3, 4),
    }


def bench_generate_big(T_prompt: int = 128, n_new: int = 256,
                       batch: int = 4, iters: int = 2) -> dict:
    """KV-cache decode at SERVING scale: a GPT-2-XL-class geometry
    (~1.26 B params — hidden 2048 x 24 layers x 16 heads, ffn 8192,
    vocab 32k), the largest standard decoder that comfortably fits one
    v5e chip's 16 GB HBM with its f32 parameters (~5 GB) plus the bf16
    KV cache. Same methodology as bench_generate; the round-4 number
    was the 4L/256h toy — this is the depth the serving path is judged
    on (VERDICT r4 weak #6)."""
    import jax
    import numpy as np

    from kubeml_tpu.models.gpt import GPTMini, GPTModule

    H, L, HEADS, FFN, V = 2048, 24, 16, 8192, 32000

    class _BigGPT(GPTMini):
        def build(self):
            return GPTModule(vocab_size=V, max_len=T_prompt + n_new,
                             hidden=H, layers=L, heads=HEADS, ffn=FFN,
                             dropout=0.0)

    jnp = jax.numpy
    model = _BigGPT()
    rng = np.random.RandomState(0)
    prompts = rng.randint(1, V, size=(batch, T_prompt)).astype(np.int32)
    variables = model.init_variables(jax.random.PRNGKey(0),
                                     {"x": jnp.asarray(prompts)})
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(variables))

    fresh = [rng.randint(1, V, size=(batch, T_prompt)).astype(np.int32)
             for _ in range(iters)]
    model.generate(variables, prompts, max_new_tokens=n_new)  # compile
    t0 = time.perf_counter()
    for p in fresh:
        out = model.generate(variables, p, max_new_tokens=n_new)
    elapsed = time.perf_counter() - t0
    assert out.shape == (batch, T_prompt + n_new)
    new_tokens = iters * batch * n_new
    return {
        "bench": "gpt_kvcache_decode_big", "params": n_params,
        "hidden": H, "layers": L, "heads": HEADS, "ffn": FFN,
        "vocab": V, "prompt_len": T_prompt, "new_tokens": n_new,
        "batch": batch,
        "decode_tokens_per_sec": round(new_tokens / elapsed, 1),
        "ms_per_generated_token": round(
            elapsed / (iters * n_new) * 1e3, 4),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--which", default="lstm,bert,flash,generate")
    ap.add_argument("--out", default=None)
    ap.add_argument("--seq", type=int, default=2048,
                    help="context length for the flash delta arm")
    ap.add_argument("--flash-batch", type=int, default=8)
    args = ap.parse_args(argv)
    which = set(args.which.split(","))

    rows = []
    if "lstm" in which:
        # BASELINE config 4 geometry: batch 64, sparse averaging plays
        # as K=8 local steps per round here (K=-1 is a data-size, not a
        # program, property — the round program is identical)
        rows.append(bench_engine_text("lstm", k=8, batch=64, seq_len=64,
                                      vocab=32000, workers=4,
                                      epoch_samples=120_000))
    if "bert" in which:
        # BASELINE config 5: K=16 local steps between merges
        rows.append(bench_engine_text("bert-tiny", k=16, batch=32,
                                      seq_len=64, vocab=30522, workers=4,
                                      epoch_samples=67_000))
    if "flash" in which:
        rows.append(bench_flash_delta("gpt", args.seq, args.flash_batch))
        rows.append(bench_flash_delta("bert", args.seq, args.flash_batch))
    if "generate" in which:
        rows.append(bench_generate())
    if "generate-big" in which:
        rows.append(bench_generate_big())

    for row in rows:
        print(json.dumps(row))
    if args.out:
        with open(args.out, "a") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
