"""Sweep driver — runs the published benchmark grids.

Equivalent of ml/experiments/train.py: picks a grid (lenet | resnet),
expands it, submits every config through the client SDK, and writes one
JSONL row per run with epoch timings, accuracies, and TTA.

Usage:
    # against a running control plane
    python -m experiments.train --grid lenet --controller http://host:port

    # self-contained on this host (boots the control plane in-process)
    python -m experiments.train --grid lenet --local --limit 4 \
        --epochs 2 --out results/lenet.jsonl

Datasets must already be registered (kubeml dataset create ...); --local
accepts --synthetic to register a small synthetic stand-in so the full
path runs anywhere.
"""

from __future__ import annotations

import argparse
import sys

from experiments.common import utils as grids
from experiments.common.experiment import KubemlExperiment, expand_grid
from experiments.common.metrics import SystemMetricsSampler

GRIDS = {
    "lenet": dict(grid=grids.LENET_GRID, epochs=grids.LENET_EPOCHS,
                  lr=grids.LENET_LR, tta=grids.LENET_TTA_GOAL,
                  function="lenet", dataset="mnist"),
    # the REAL-data arm (experiments/data.py): genuine handwritten
    # digits, epoch shuffling on (the real-data sweeps want convergence)
    "lenet-digits": dict(grid=grids.LENET_DIGITS_GRID,
                         epochs=grids.LENET_DIGITS_EPOCHS,
                         lr=grids.LENET_DIGITS_LR,
                         tta=grids.LENET_DIGITS_TTA_GOAL,
                         function="lenet", dataset="digits",
                         shuffle=True, real="digits"),
    # matched-global-batch local-SGD study (explicit config list — the
    # fair N>1 comparison; see LENET_DIGITS_GBATCH_CONFIGS)
    "lenet-digits-gbatch": dict(grid=grids.LENET_DIGITS_GBATCH_CONFIGS,
                                epochs=grids.LENET_DIGITS_GBATCH_EPOCHS,
                                lr=grids.LENET_DIGITS_LR,
                                tta=grids.LENET_DIGITS_TTA_GOAL,
                                function="lenet", dataset="digits",
                                shuffle=True, real="digits"),
    # REAL-data dynamic-parallelism arm: the live throughput policy
    # driving a genuine-image job (the real-data sibling of the
    # resnet50 synthetic autoscale run — docs/performance.md)
    "lenet-digits-autoscale": dict(
        grid=grids.LENET_DIGITS_AUTOSCALE_GRID,
        epochs=grids.LENET_DIGITS_EPOCHS, lr=grids.LENET_DIGITS_LR,
        tta=grids.LENET_DIGITS_TTA_GOAL, function="lenet",
        dataset="digits", shuffle=True, real="digits", static=False),
    "resnet": dict(grid=grids.RESNET_GRID, epochs=grids.RESNET_EPOCHS,
                   lr=grids.RESNET_LR, tta=grids.RESNET_TTA_GOAL,
                   function="resnet18", dataset="cifar10"),
    # BASELINE.json configs 3-5
    "resnet50": dict(grid=grids.RESNET50_GRID, epochs=grids.RESNET50_EPOCHS,
                     lr=grids.RESNET50_LR, tta=grids.RESNET50_TTA_GOAL,
                     function="resnet50", dataset="imagenette",
                     static=False),  # dynamic-parallelism autoscale
    "lstm": dict(grid=grids.LSTM_GRID, epochs=grids.LSTM_EPOCHS,
                 lr=grids.LSTM_LR, tta=grids.LSTM_TTA_GOAL,
                 function="lstm", dataset="agnews"),
    "bert": dict(grid=grids.BERT_GRID, epochs=grids.BERT_EPOCHS,
                 lr=grids.BERT_LR, tta=grids.BERT_TTA_GOAL,
                 function="bert-tiny", dataset="sst2"),
}


# synthetic stand-in spec per sweep function: image functions get float
# images, text functions get padded int token sequences
_SYNTH = {
    "lenet": dict(shape=(28, 28, 1), classes=10),
    "resnet18": dict(shape=(32, 32, 3), classes=10),
    "resnet34": dict(shape=(32, 32, 3), classes=10),
    "resnet50": dict(shape=(64, 64, 3), classes=10),
    "vgg11": dict(shape=(32, 32, 3), classes=10),
    "mlp": dict(shape=(8,), classes=3),
    "lstm": dict(seq_len=64, vocab=32000, classes=4),
    "bert-tiny": dict(seq_len=64, vocab=30522, classes=2),
}


def make_synthetic_split(function: str, n: int, rng) -> tuple:
    """One (x, y) synthetic split for a sweep function — shared by the
    distributed driver and the single-node baseline arm so both train on
    identically-distributed data (text: ragged token ids, pad id 0)."""
    import numpy as np

    spec = _SYNTH[function]
    if "seq_len" in spec:
        T = spec["seq_len"]
        x = rng.randint(1, spec["vocab"], (n, T)).astype(np.int32)
        lengths = rng.randint(T // 4, T + 1, n)
        x[np.arange(T)[None, :] >= lengths[:, None]] = 0
    else:
        x = rng.rand(n, *spec["shape"]).astype(np.float32)
    y = rng.randint(0, spec["classes"], n).astype(np.int64)
    return x, y


def _register_synthetic(client, name: str, function: str) -> None:
    import tempfile

    import numpy as np

    rng = np.random.RandomState(0)
    with tempfile.TemporaryDirectory() as d:
        paths = {}
        for split, n in (("train", 512), ("test", 128)):
            x, y = make_synthetic_split(function, n, rng)
            np.save(f"{d}/x_{split}.npy", x)
            np.save(f"{d}/y_{split}.npy", y)
            paths[split] = (f"{d}/x_{split}.npy", f"{d}/y_{split}.npy")
        client.v1().datasets().create(
            name, paths["train"][0], paths["train"][1],
            paths["test"][0], paths["test"][1])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--grid", choices=sorted(GRIDS), required=True)
    ap.add_argument("--controller", default=None,
                    help="controller URL; omit with --local")
    ap.add_argument("--local", action="store_true",
                    help="boot the control plane in-process")
    ap.add_argument("--synthetic", action="store_true",
                    help="register a synthetic dataset if missing")
    ap.add_argument("--epochs", type=int, default=None)
    ap.add_argument("--limit", type=int, default=None,
                    help="run only the first N grid configs")
    ap.add_argument("--offset", type=int, default=0,
                    help="skip the first N grid configs (chunked sweeps)")
    ap.add_argument("--out", default=None, help="results JSONL path")
    ap.add_argument("--metrics-out", default=None,
                    help="system-metrics JSON path")
    args = ap.parse_args(argv)

    spec = GRIDS[args.grid]
    dep = None
    if args.local:
        from kubeml_tpu.control.deployment import start_deployment
        dep = start_deployment()
        controller = dep.controller_url
    else:
        controller = args.controller

    from kubeml_tpu.control.client import KubemlClient
    client = KubemlClient(controller)
    exp = KubemlExperiment(client)

    try:
        names = [d.name for d in client.v1().datasets().list()]
        if spec["dataset"] not in names:
            if spec.get("real") == "digits":
                from experiments.data import real_digits, register_arrays
                register_arrays(client, spec["dataset"], *real_digits())
            elif not args.synthetic:
                print(f"dataset {spec['dataset']} not registered "
                      f"(use kubeml dataset create, or --synthetic)",
                      file=sys.stderr)
                return 1
            else:
                _register_synthetic(client, spec["dataset"],
                                    spec["function"])

        # a grid may be a dict of lists (cartesian product) or an
        # explicit list of coupled configs (matched-global-batch arms)
        configs = (list(spec["grid"]) if isinstance(spec["grid"], list)
                   else expand_grid(spec["grid"]))
        if args.offset:
            configs = configs[args.offset:]
        if args.limit:
            configs = configs[: args.limit]
        epochs = args.epochs or spec["epochs"]
        sampler = SystemMetricsSampler().start()
        for i, cfg in enumerate(configs):
            req = exp.make_request(
                function=spec["function"], dataset=spec["dataset"],
                epochs=epochs, batch=cfg["batch"], lr=spec["lr"],
                parallelism=cfg["parallelism"], k=cfg["k"],
                static=spec.get("static", True),
                shuffle=spec.get("shuffle", False),
                max_parallelism=cfg.get("max_parallelism", 0))
            res = exp.run(req, config={"function": spec["function"],
                                       "dataset": spec["dataset"],
                                       "epochs": epochs, "lr": spec["lr"],
                                       "static": spec.get("static", True),
                                       "shuffle": spec.get("shuffle",
                                                           False),
                                       **cfg})
            row = res.row([spec["tta"]])
            print(f"[{i + 1}/{len(configs)}] {row}")
        sampler.stop()
        if args.out:
            exp.save_jsonl(args.out, [spec["tta"]])
        if args.metrics_out:
            sampler.save(args.metrics_out)
        return 0
    finally:
        if dep is not None:
            dep.stop()


if __name__ == "__main__":
    sys.exit(main())
