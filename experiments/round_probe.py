"""Round-dispatch attribution probe (round 5).

The round-3 conv probe (`experiments/conv_probe.py`) attributed the
engine's gap to its own grads-only ceiling as optimizer apply (~6%)
plus merge/stats/masking (~3%) — leaving ~6-7% unexplained. The last
suspect is PER-ROUND DISPATCH: the production epoch loop submits one
jitted round per sync round (kubeml_tpu/train/job.py), and on a
tunneled backend each submission costs host work + wire latency that
the round's ~50 ms of compute may not fully hide.

Arms (all readback-synchronized, fresh rng values per dispatch so no
backend result cache can serve them):

  per_round      the production path: N single-round dispatches
  scan_R         N/R dispatches of an R-round lax.scan (identical math,
                 merges between rounds preserved) for R in {2, 4, 8}
  host_staged    per_round with the full sample tensor device_put every
                 dispatch — the job's fallback staging cost, unhidden
  cache_per_round / cache_scan_4
                 index-fed rounds against the HBM-resident dataset
                 cache (data/device_cache.py): dispatches carry only
                 [.., W, S, B] int32 gather indices
  grads_only     the round-3 ceiling re-measured through THIS harness:
                 K-step scan of fwd+bwd with summed grads, no optimizer,
                 no merge — per-round dispatches
  grads_scan_8   the same, 8 rounds per dispatch
  bucketed_4mb   per_round with the merge split into 4 MB buckets whose
                 psums issue as their leaves finalize (parallel/merge.py
                 overlap lever), lax apply — isolates bucketing/overlap
  fused_merge    bucketed_4mb with the fused merge+optimizer Pallas
                 kernel auto-enabled (ops/pallas/fused_merge.py; lax
                 fallback on CPU, so the delta only shows on TPU)
  ef_bf16 / ef_int8
                 per_round with error-feedback compressed merge payloads
                 (2x / ~4x fewer cross-slice wire bytes, residual carry
                 in the round program)

If scan_R recovers most of (ceiling - per_round), the residual gap is
dispatch, and batching rounds per dispatch is the fix; if it moves
nothing, the gap is intrinsic compute and the honest answer is a doc
paragraph.

Usage: python -m experiments.round_probe [--out results/round_probe.jsonl]
"""

from __future__ import annotations

import argparse
import json
import math
import time

BATCH = 256
K = 8
ROUNDS = 24          # total rounds per timed arm (divisible by 2,4,8)
WARM_ROUNDS = 8


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.parallel.kavg import KAvgEngine, masked_scalar_loss
    from kubeml_tpu.parallel.mesh import make_mesh

    n_chips = len(jax.devices())
    mesh = make_mesh(n_data=n_chips)
    model = get_builtin("resnet18")()
    rng = np.random.RandomState(0)
    W, S, B = n_chips, K, BATCH
    x = rng.rand(W, S, B, 32, 32, 3).astype(np.float32)
    y = rng.randint(0, 10, size=(W, S, B)).astype(np.int32)
    batch = {"x": jnp.asarray(x), "y": jnp.asarray(y)}
    masks = dict(sample_mask=np.ones((W, S, B), np.float32),
                 step_mask=np.ones((W, S), np.float32),
                 worker_mask=np.ones(W, np.float32))
    variables = model.init_variables(
        jax.random.PRNGKey(0), {"x": jnp.asarray(x[0, 0])})
    rows = []

    def emit(name, seconds, rounds):
        sps = rounds * W * S * B / seconds / n_chips
        row = {"arm": name, "seconds": round(seconds, 4),
               "rounds": rounds,
               "samples_per_sec_per_chip": round(sps, 1)}
        rows.append(row)
        print(json.dumps(row), flush=True)

    def anchor(tree):
        leaf = jax.tree_util.tree_leaves(tree)[0]
        return np.asarray(leaf.ravel()[:1])

    # ---- arm: production per-round dispatch --------------------------
    engine = KAvgEngine(mesh, model.loss, model.metrics,
                        model.configure_optimizers, donate=False)

    def per_round(n, vars_):
        for i in range(n):
            rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)
            vars_, _ = engine.train_round(vars_, batch, rngs=rngs,
                                          lr=0.1, epoch=0, **masks)
        anchor(vars_)
        return vars_

    variables = per_round(WARM_ROUNDS, variables)
    t0 = time.perf_counter()
    variables = per_round(ROUNDS, variables)
    emit("per_round", time.perf_counter() - t0, ROUNDS)

    # ---- arms: R rounds per dispatch ---------------------------------
    for R in (2, 4, 8):
        eng = KAvgEngine(mesh, model.loss, model.metrics,
                         model.configure_optimizers, donate=False)
        stack = lambda a: np.broadcast_to(a, (R,) + a.shape).copy()
        rbatch = {k: jnp.asarray(stack(np.asarray(v)))
                  for k, v in (("x", x), ("y", y))}
        rmasks = {k: stack(v) for k, v in masks.items()}

        def multi(n, vars_):
            for i in range(n // R):
                rngs = rng.randint(0, 2**31,
                                   size=(R, W, S, 2)).astype(np.uint32)
                vars_, _ = eng.train_rounds(vars_, rbatch, rngs=rngs,
                                            lr=0.1, epoch=0, **rmasks)
            anchor(vars_)
            return vars_

        v2 = multi(WARM_ROUNDS, variables)
        t0 = time.perf_counter()
        v2 = multi(ROUNDS, v2)
        emit(f"scan_{R}", time.perf_counter() - t0, ROUNDS)

    # ---- arms: merge overlap / compression levers --------------------
    # Same device-resident per-round loop as per_round, fresh engine per
    # arm so each compiles its own round program. bucketed_4mb splits
    # the merge into size-capped buckets whose psums issue early (lax
    # apply, merge_fused=False); fused_merge layers the Pallas
    # merge-apply kernel on top (auto-gated: TPU only, lax fallback
    # elsewhere — on CPU this arm should match bucketed_4mb); the EF
    # arms compress the cross-slice payload with residual carry. Deltas
    # vs per_round attribute each lever; the comm proxy row records the
    # deterministic wire plan next to the measured time.
    merge_arms = (
        ("bucketed_4mb", dict(merge_bucket_mb=4.0, merge_fused=False)),
        ("fused_merge", dict(merge_bucket_mb=4.0)),
        ("ef_bf16", dict(merge_compress="bf16")),
        ("ef_int8", dict(merge_compress="int8")),
    )
    for arm_name, merge_kw in merge_arms:
        eng = KAvgEngine(mesh, model.loss, model.metrics,
                         model.configure_optimizers, donate=False,
                         **merge_kw)

        def merge_arm(n, vars_):
            for i in range(n):
                rngs = rng.randint(0, 2**31,
                                   size=(W, S, 2)).astype(np.uint32)
                vars_, _ = eng.train_round(vars_, batch, rngs=rngs,
                                           lr=0.1, epoch=0, **masks)
            anchor(vars_)
            return vars_

        vm = merge_arm(WARM_ROUNDS, variables)
        t0 = time.perf_counter()
        vm = merge_arm(ROUNDS, vm)
        seconds = time.perf_counter() - t0
        sps = ROUNDS * W * S * B / seconds / n_chips
        row = {"arm": arm_name, "seconds": round(seconds, 4),
               "rounds": ROUNDS,
               "samples_per_sec_per_chip": round(sps, 1),
               "comm_proxy": eng.merge_comm_proxy(variables)}
        rows.append(row)
        print(json.dumps(row), flush=True)

    # ---- arms: dispatch-payload attribution (device cache) -----------
    # The per_round/scan_R arms above hold the batch DEVICE-RESIDENT, so
    # they measure pure dispatch overhead with zero feeding cost. These
    # three isolate the payload term the production job actually pays:
    # host_staged re-uploads the full sample tensor every dispatch (the
    # job's fallback staging path), cache_per_round ships only [W, S, B]
    # int32 indices against an HBM-resident slab cache
    # (data/device_cache.py), cache_scan_4 stacks 4 index-fed rounds per
    # dispatch (the [R, W, S, B] composition with rounds_per_dispatch).
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubeml_tpu.data.device_cache import DeviceDatasetCache
    from kubeml_tpu.parallel.mesh import DATA_AXIS

    b_sh = NamedSharding(mesh, P(DATA_AXIS))

    def host_staged(n, vars_):
        for i in range(n):
            rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)
            staged = {"x": jax.device_put(x, b_sh),
                      "y": jax.device_put(y, b_sh)}
            vars_, _ = engine.train_round(vars_, staged, rngs=rngs,
                                          lr=0.1, epoch=0, **masks)
        anchor(vars_)
        return vars_

    v3 = host_staged(WARM_ROUNDS, variables)
    t0 = time.perf_counter()
    v3 = host_staged(ROUNDS, v3)
    emit("host_staged", time.perf_counter() - t0, ROUNDS)

    cache = DeviceDatasetCache.from_arrays(
        mesh, {"x": x.reshape(W * S * B, 32, 32, 3),
               "y": y.reshape(W * S * B)}, layout="sharded")
    # worker w's slab is its S*B contiguous samples, so lane-local
    # indices are the same [S, B] arange for every worker
    idx1 = np.broadcast_to(
        np.arange(S * B, dtype=np.int32).reshape(S, B), (W, S, B)).copy()

    def cache_per_round(n, vars_):
        for i in range(n):
            rngs = rng.randint(0, 2**31, size=(W, S, 2)).astype(np.uint32)
            vars_, _ = engine.train_round_indexed(
                vars_, cache, jax.device_put(idx1, b_sh), rngs=rngs,
                lr=0.1, epoch=0, **masks)
        anchor(vars_)
        return vars_

    v3 = cache_per_round(WARM_ROUNDS, variables)
    t0 = time.perf_counter()
    v3 = cache_per_round(ROUNDS, v3)
    emit("cache_per_round", time.perf_counter() - t0, ROUNDS)

    Rc = 4
    idxR = np.broadcast_to(idx1, (Rc,) + idx1.shape).copy()
    idxR_sh = NamedSharding(mesh, P(None, DATA_AXIS))
    cmasks = {k: np.broadcast_to(v, (Rc,) + v.shape).copy()
              for k, v in masks.items()}

    def cache_scan(n, vars_):
        for i in range(n // Rc):
            rngs = rng.randint(0, 2**31,
                               size=(Rc, W, S, 2)).astype(np.uint32)
            vars_, _ = engine.train_rounds_indexed(
                vars_, cache, jax.device_put(idxR, idxR_sh), rngs=rngs,
                lr=0.1, epoch=0, **cmasks)
        anchor(vars_)
        return vars_

    v3 = cache_scan(WARM_ROUNDS, variables)
    t0 = time.perf_counter()
    v3 = cache_scan(ROUNDS, v3)
    emit(f"cache_scan_{Rc}", time.perf_counter() - t0, ROUNDS)

    # ---- arms: grads-only ceiling through this harness ---------------
    ones = np.ones((B,), np.float32)

    def grads_round(params, model_state, xb, yb, keys):
        def step(carry, xs):
            p, st = carry
            xi, yi, key = xs
            scalar = masked_scalar_loss(
                model.loss, st, {"x": xi, "y": yi}, key,
                jnp.asarray(ones))
            (loss, new_st), grads = jax.value_and_grad(
                scalar, has_aux=True)(p)
            # consume grads nonlinearly so nothing hoists/factors
            p = jax.tree_util.tree_map(
                lambda a, g: a - 1e-6 * g * g, p, grads)
            return (p, new_st), loss

        (params, model_state), losses = jax.lax.scan(
            step, (params, model_state), (xb, yb, keys), unroll=K)
        return params, model_state, losses.sum()

    g_single = jax.jit(grads_round)

    def grads_scan(params, model_state, xbs, ybs, keyss):
        def one(carry, xs):
            p, st = carry
            xb, yb, keys = xs
            p, st, loss = grads_round(p, st, xb, yb, keys)
            return (p, st), loss

        (params, model_state), losses = jax.lax.scan(
            one, (params, model_state), (xbs, ybs, keyss))
        return params, model_state, losses.sum()

    g_multi = jax.jit(grads_scan)

    params = variables["params"]
    mstate = {k: v for k, v in variables.items() if k != "params"}
    xb, yb = jnp.asarray(x[0]), jnp.asarray(y[0])

    def run_grads(n, p, st):
        for i in range(n):
            keys = rng.randint(0, 2**31, size=(S, 2)).astype(np.uint32)
            p, st, _ = g_single(p, st, xb, yb, jnp.asarray(keys))
        anchor(p)
        return p, st

    p, st = run_grads(WARM_ROUNDS, params, mstate)
    t0 = time.perf_counter()
    p, st = run_grads(ROUNDS, p, st)
    # grads arms run one worker's shard per dispatch (W=1 equivalent):
    # normalize per chip by the samples actually processed
    emit("grads_only", time.perf_counter() - t0, ROUNDS / W)

    def run_grads8(n, p, st):
        for i in range(n // 8):
            keys = rng.randint(0, 2**31,
                               size=(8, S, 2)).astype(np.uint32)
            p, st, _ = g_multi(
                p, st, jnp.broadcast_to(xb, (8,) + xb.shape),
                jnp.broadcast_to(yb, (8,) + yb.shape), jnp.asarray(keys))
        anchor(p)
        return p, st

    p, st = run_grads8(WARM_ROUNDS, p, st)
    t0 = time.perf_counter()
    p, st = run_grads8(ROUNDS, p, st)
    emit("grads_scan_8", time.perf_counter() - t0, ROUNDS / W)

    if args.out:
        with open(args.out, "a") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
