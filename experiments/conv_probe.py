"""Kernel-level conv cost attribution for the ResNet-18/CIFAR headline.

Round-2's ablation (docs/performance.md) ended at "~28% MFU, the ceiling
is conv kernel efficiency" without attributing WHERE inside the model the
cycles go. This probe measures, per ResNet-18 conv shape on the attached
chip:

  1. a peak-matmul reference (what the MXU actually delivers here);
  2. shape-matched matmuls (the im2col-equivalent GEMM for each conv,
     isolating the lane-occupancy effect of narrow channel counts);
  3. each conv forward alone;
  4. conv + train-mode BatchNorm + ReLU (the real per-layer block,
     exposing the bandwidth cost of the BN statistics passes);
  5. each conv's backward (input + filter grads);
  6. whole-model forward and train-step for cross-checking.

Timing: every probe runs K iterations over K distinct inputs inside ONE
jitted lax.scan (per-dispatch host/tunnel cost on this relay is ~ms —
single-op dispatch timing would be pure noise), accumulating a scalar
that is read back once. The scalar sum adds one output read pass per
iteration; at the arithmetic intensities probed here that is <10% and it
is identical across variants, so comparisons stay clean.

The K distinct inputs are derived ON DEVICE from one staged base array
(per-iteration scale factors): distinct enough to defeat loop-invariant
hoisting across scan iterations, without staging K full copies through
the tunnel (generating/transferring gigabytes of host randoms was the
first version's bottleneck, not the probes themselves).

Usage: python experiments/conv_probe.py [--batch 256] [--iters 24]
Writes one JSON line per probe to stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


_NULL_BASELINE = None


def _timed_raw(op, iters, *operands, n_timed=3):
    idxs = jnp.arange(iters, dtype=jnp.int32)

    @jax.jit
    def run(idxs, *operands):
        def body(carry, i):
            y = op(i, *operands)
            # consume NONLINEARLY: a plain sum(conv(x, w)) lets XLA
            # factor the reduction through the (linear) kernel and skip
            # computing the full output — observed as impossible >peak
            # "TFLOPs" on this chip. sum(y*y) cannot be factored; it
            # costs one fused elementwise pass over y (~10% on the
            # biggest outputs, identical across compared variants).
            y = y.astype(jnp.float32)
            return carry + (y * y).sum(), None

        out, _ = lax.scan(body, jnp.float32(0.0), idxs)
        return out

    np.asarray(run(idxs, *operands))  # compile + warm transfer path
    times = []
    for _ in range(n_timed):
        t0 = time.perf_counter()
        np.asarray(run(idxs, *operands))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def _timed_scan(op, iters, *operands, n_timed=3):
    """Median wall-clock seconds for one jitted scan of
    `op(i, *operands)` over `iters` distinct int32 indices i, with the
    per-call constant cost SUBTRACTED.

    On this tunneled backend a single dispatch+scalar-readback costs
    ~100-150 ms — orders of magnitude above the kernels being measured —
    so (a) the scan amortizes over many iterations and (b) a null scan
    (same dispatch/readback, trivial body) is measured once and its
    median subtracted; the probes report device compute, not tunnel
    latency.

    The op must make each step's inputs distinct via a NON-FACTORABLE
    transform of its SMALL operand — `jnp.roll(w, i, axis)` — so the
    kernel cannot be hoisted out of the loop. A scalar scale does NOT
    work: matmul/conv are linear in the weights, so XLA rewrites
    op(x, w*s) as s*op(x, w) and hoists the entire kernel (first
    version of this probe reported 340 "TF/s" on a 200 TF/s chip that
    way). The roll costs one copy of the small operand per iteration —
    negligible for conv weights, ~10% on the 4096-square peak probe
    (noted inline).

    operands are jit ARGUMENTS, not closures: closure-captured arrays
    embed as constants in the serialized HLO, and this backend's
    remote-compile endpoint rejects oversized programs (HTTP 413)."""
    global _NULL_BASELINE
    if _NULL_BASELINE is None:
        _NULL_BASELINE = _timed_raw(
            lambda i: (i * 2).astype(jnp.float32), iters, n_timed=5)
        print(json.dumps({"probe": "null_dispatch_readback",
                          "ms": round(_NULL_BASELINE * 1e3, 2)}),
              flush=True)
    t = _timed_raw(op, iters, *operands, n_timed=n_timed)
    work = t - _NULL_BASELINE
    # the null baseline jitters ±~15ms call-to-call on the tunnel; when
    # the subtracted work is small the error dominates (observed as
    # impossible >100%-of-peak readings on the fast shapes). Re-measure
    # with enough iterations that work >= ~0.4s/call (one extra compile
    # for the small shapes; per-iter cost then has <5% baseline error).
    if work < 0.4:
        scale = min(16, max(2, int(np.ceil(0.4 / max(work, 0.005)))))
        t2 = _timed_raw(op, iters * scale, *operands, n_timed=n_timed)
        return max((t2 - _NULL_BASELINE) / scale, 1e-9)
    return max(work, 1e-9)


def _report(name, secs, iters, flops, extra=None):
    tflops = flops * iters / secs / 1e12
    line = {"probe": name, "ms_per_iter": round(secs / iters * 1e3, 4),
            "tflops": round(tflops, 2)}
    if extra:
        line.update(extra)
    print(json.dumps(line), flush=True)
    return tflops


# ResNet-18 CIFAR conv inventory: (name, H, W, Cin, Cout, kernel, stride)
SHAPES = [
    ("stem_3x3_3to64_32", 32, 3, 64, 3, 1),
    ("s1_3x3_64to64_32", 32, 64, 64, 3, 1),
    ("s2_3x3_64to128_s2", 32, 64, 128, 3, 2),
    ("s2_3x3_128to128_16", 16, 128, 128, 3, 1),
    ("s2_1x1_64to128_s2", 32, 64, 128, 1, 2),
    ("s3_3x3_128to256_s2", 16, 128, 256, 3, 2),
    ("s3_3x3_256to256_8", 8, 256, 256, 3, 1),
    ("s4_3x3_256to512_s2", 8, 256, 512, 3, 2),
    ("s4_3x3_512to512_4", 4, 512, 512, 3, 1),
]


def conv_flops(B, H, Cin, Cout, k, stride):
    Ho = H // stride
    return 2.0 * B * Ho * Ho * Cin * Cout * k * k


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--iters", type=int, default=512)
    ap.add_argument("--only-model", action="store_true",
                    help="skip the per-shape probes; run the whole-model "
                         "forward/train attribution only")
    args = ap.parse_args()
    B, K = args.batch, args.iters
    rng = np.random.RandomState(0)

    dev = jax.devices()[0]
    print(json.dumps({"probe": "device", "platform": dev.platform,
                      "kind": getattr(dev, "device_kind", "?")}), flush=True)

    # --- 1. peak matmul reference ------------------------------------
    M = N = Kdim = 4096
    a = jnp.asarray(rng.rand(M, Kdim).astype(np.float32), jnp.bfloat16)
    b = jnp.asarray(rng.rand(Kdim, N).astype(np.float32), jnp.bfloat16)
    # roll costs one b copy per iter (~12% of the dot here — the peak
    # number understates true peak by about that much; fine for a
    # reference bar the conv probes are compared against)
    secs = _timed_scan(
        lambda i, a, b: jnp.dot(a, jnp.roll(b, i, axis=0),
                                preferred_element_type=jnp.float32),
        K, a, b)
    peak = _report("matmul_4096", secs, K, 2.0 * M * N * Kdim)
    del a, b

    # --- 2. im2col-equivalent GEMMs per conv shape -------------------
    for name, H, Cin, Cout, k, stride in ([] if args.only_model
                                          else SHAPES):
        Ho = H // stride
        Mrows = B * Ho * Ho
        Kc = Cin * k * k
        a = jnp.asarray(rng.rand(Mrows, Kc).astype(np.float32),
                        jnp.bfloat16)
        bm = jnp.asarray(rng.rand(Kc, Cout).astype(np.float32),
                         jnp.bfloat16)
        secs = _timed_scan(
            lambda i, a, bm: jnp.dot(a, jnp.roll(bm, i, axis=1),
                                     preferred_element_type=jnp.float32),
            K, a, bm)
        fl = 2.0 * Mrows * Kc * Cout
        _report(f"gemm[{name}]", secs, K, fl,
                {"pct_peak": round(100 * (fl * K / secs / 1e12) / peak, 1)})
        del a, bm

    # --- 3/4/5. convs: fwd, fwd+bn+relu, bwd -------------------------
    total_fwd = {}
    for name, H, Cin, Cout, k, stride in ([] if args.only_model
                                          else SHAPES):
        x = jnp.asarray(rng.rand(B, H, H, Cin).astype(np.float32),
                        jnp.bfloat16)
        w = jnp.asarray(rng.rand(k, k, Cin, Cout).astype(np.float32)
                        * 0.05, jnp.bfloat16)
        fl = conv_flops(B, H, Cin, Cout, k, stride)
        dn = lax.conv_dimension_numbers(
            (B, H, H, Cin), (k, k, Cin, Cout), ("NHWC", "HWIO", "NHWC"))

        # bf16 in/out with no preferred_element_type — exactly what the
        # model's flax Conv(dtype=bf16) lowers to
        def conv(i, x, w, dn=dn, stride=stride):
            return lax.conv_general_dilated(
                x, jnp.roll(w, i, axis=3), (stride, stride), "SAME",
                dimension_numbers=dn)

        secs = _timed_scan(conv, K, x, w)
        _report(f"conv_fwd[{name}]", secs, K, fl,
                {"pct_peak": round(100 * (fl * K / secs / 1e12) / peak, 1)})
        total_fwd[name] = secs / K

        # conv + train-mode BN (batch stats) + relu
        def conv_bn_relu(i, x, w, dn=dn, stride=stride):
            y = lax.conv_general_dilated(
                x, jnp.roll(w, i, axis=3), (stride, stride), "SAME",
                dimension_numbers=dn)
            # f32 statistics over the bf16 conv output — flax BatchNorm's
            # layout (param_dtype f32)
            yf = y.astype(jnp.float32)
            mean = yf.mean(axis=(0, 1, 2))
            var = ((yf - mean) ** 2).mean(axis=(0, 1, 2))
            yn = (yf - mean) * lax.rsqrt(var + 1e-5)
            return nn_relu(yn).astype(jnp.bfloat16)

        secs_bn = _timed_scan(conv_bn_relu, K, x, w)
        _report(f"conv_bn_relu[{name}]", secs_bn, K, fl,
                {"bn_overhead_pct": round(100 * (secs_bn - secs) / secs, 1)})

        # backward: grads wrt (x, w) of sum(conv^2) — the SQUARED loss
        # makes the cotangent 2y (input-dependent), so neither transposed
        # conv is loop-invariant (with sum(y), the cotangent is constant
        # ones and the filter-grad conv hoists out of the timing loop).
        # All-bf16 conv so the transposes see bf16 cotangents.
        def conv_loss(xi_w, dn=dn, stride=stride):
            xi, wi = xi_w
            y = lax.conv_general_dilated(
                xi, wi, (stride, stride), "SAME", dimension_numbers=dn)
            return (y * y).sum(dtype=jnp.float32)

        grad_fn = jax.grad(conv_loss)

        def bwd(i, x, w, grad_fn=grad_fn):
            gx, gw = grad_fn((x, jnp.roll(w, i, axis=3)))
            return gx.sum() + gw.sum()

        # FLOPs: the squared loss needs the forward conv's output for
        # its cotangent (2y), so grads-of-both = fwd recompute + input-
        # grad conv + filter-grad conv = 3*fl (NOT 2*fl — the first
        # committed run under-credited the backward by a third)
        secs_b = _timed_scan(bwd, K, x, w)
        _report(f"conv_bwd[{name}]", secs_b, K, 3 * fl,
                {"pct_peak": round(100 * (3 * fl * K / secs_b / 1e12)
                                   / peak, 1),
                 "vs_fwd": round(secs_b / secs, 2)})

        # split attribution: input-grad (transposed conv) vs filter-grad
        # (the batch-spatial correlation) — they have very different
        # TPU lowerings, and which one is slow decides where a custom
        # kernel could pay
        gx_fn = jax.grad(conv_loss)

        def bwd_gx(i, x, w, gx_fn=gx_fn):
            gx, _ = gx_fn((x, jnp.roll(w, i, axis=3)))
            return gx.sum()

        def bwd_gw(i, x, w, gx_fn=gx_fn):
            _, gw = gx_fn((x, jnp.roll(w, i, axis=3)))
            return gw.sum()

        for tag, fn in (("gx", bwd_gx), ("gw", bwd_gw)):
            # each runs fwd + ONE grad (DCE removes the other): fl for
            # the fwd recompute + fl for the grad conv
            s = _timed_scan(fn, K, x, w)
            _report(f"conv_bwd_{tag}[{name}]", s, K, 2 * fl,
                    {"pct_peak": round(100 * (2 * fl * K / s / 1e12)
                                       / peak, 1)})
        del x

    # --- 6. whole model cross-check ----------------------------------
    from kubeml_tpu.models import get_builtin

    model = get_builtin("resnet18")()
    xb = jnp.asarray(rng.rand(B, 32, 32, 3).astype(np.float32))
    yb = jnp.asarray(rng.randint(0, 10, size=(B,)).astype(np.int32))
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": xb})
    # stage multiplicities for resnet18: stem x1, s1 conv x4, downsample
    # convs x1 each, same-size convs x3 each (first block conv2 + block2's
    # 2); the three 1x1 projs at s2/s3/s4 are ~4% of model FLOPs and the
    # estimate carries only the s2 one — attribution, not accounting
    mult = {"stem_3x3_3to64_32": 1, "s1_3x3_64to64_32": 4,
            "s2_3x3_64to128_s2": 1, "s2_3x3_128to128_16": 3,
            "s2_1x1_64to128_s2": 1, "s3_3x3_128to256_s2": 1,
            "s3_3x3_256to256_8": 3, "s4_3x3_256to512_s2": 1,
            "s4_3x3_512to512_4": 3}
    model_flops_fwd = sum(conv_flops(B, H, Cin, Cout, k, s) * mult[nm]
                          for nm, H, Cin, Cout, k, s in SHAPES)
    est_fwd = sum(total_fwd[nm] * mult[nm] for nm in total_fwd)

    def fwd(i, variables, xb):
        # batch-axis roll: same samples, non-factorable variation
        return model.module.apply(variables, jnp.roll(xb, i, axis=0),
                                  train=False)

    secs = _timed_scan(fwd, K, variables, xb)
    _report("model_fwd", secs, K, model_flops_fwd,
            {"sum_of_conv_fwd_ms": round(est_fwd * 1e3, 3),
             "pct_peak": round(100 * (model_flops_fwd * K / secs / 1e12)
                               / peak, 1)})

    ones = jnp.ones((B,), jnp.float32)
    key = jax.random.PRNGKey(1)

    def train_grads(i, variables, xb, yb):
        def scalar(params):
            per_ex, new_state = model.loss(
                {**variables, "params": params},
                {"x": jnp.roll(xb, i, axis=0),
                 "y": jnp.roll(yb, i, axis=0)}, key, ones)
            return per_ex.mean(), new_state
        (loss, _), grads = jax.value_and_grad(scalar, has_aux=True)(
            variables["params"])
        # consume every grad leaf so nothing dead-code-eliminates
        return sum(g.sum().astype(jnp.float32)
                   for g in jax.tree_util.tree_leaves(grads)) + loss

    secs = _timed_scan(train_grads, K, variables, xb, yb)
    _report("model_train_step(grads_only)", secs, K, 3 * model_flops_fwd,
            {"samples_per_sec": round(K * B / secs, 1)})


def nn_relu(x):
    return jnp.maximum(x, 0)


if __name__ == "__main__":
    main()
