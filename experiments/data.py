"""Real datasets available in a zero-egress environment.

The reference's published protocol runs on MNIST and CIFAR-10
(ml/experiments/README.md:1-21). This build environment has no network
egress and ships no MNIST/CIFAR archives, so the real-data arm of the
protocol runs on the one real image dataset baked into the image:
scikit-learn's bundled `digits` (1,797 genuine 8x8 handwritten digit
scans, the UCI Optical Recognition of Handwritten Digits set). The
images are zero-padded onto the MNIST 28x28 canvas — padding embeds the
real pixels unchanged, so the LeNet/MNIST configs run verbatim — and
split 80/20 with per-class stratification. Convergence, TTA, and
epoch-time numbers from this arm are REAL measured training; only the
absolute dataset scale differs from MNIST (documented alongside the
results in docs/performance.md).
"""

from __future__ import annotations

import os
import tempfile

import numpy as np


def real_digits(canvas: int = 28):
    """(x_train, y_train, x_test, y_test): real handwritten digits on a
    canvas x canvas x 1 float32 grid in [0, 1], stratified 80/20."""
    from sklearn.datasets import load_digits

    d = load_digits()
    images = (d.images / 16.0).astype(np.float32)  # native range 0..16
    labels = d.target.astype(np.int64)

    n, h, w = images.shape
    pad_top = (canvas - h) // 2
    pad_left = (canvas - w) // 2
    x = np.zeros((n, canvas, canvas, 1), np.float32)
    x[:, pad_top:pad_top + h, pad_left:pad_left + w, 0] = images

    # deterministic stratified split: within each class, every 5th
    # sample (by dataset order) goes to test
    test_mask = np.zeros(n, bool)
    for c in range(10):
        idx = np.flatnonzero(labels == c)
        test_mask[idx[::5]] = True
    return (x[~test_mask], labels[~test_mask],
            x[test_mask], labels[test_mask])


def register_arrays(client, name: str, x_train, y_train, x_test, y_test
                    ) -> None:
    """Register four arrays as a dataset through the public upload API."""
    with tempfile.TemporaryDirectory() as d:
        paths = []
        for fname, arr in (("xtr", x_train), ("ytr", y_train),
                           ("xte", x_test), ("yte", y_test)):
            p = os.path.join(d, f"{fname}.npy")
            np.save(p, arr)
            paths.append(p)
        client.v1().datasets().create(name, *paths)
