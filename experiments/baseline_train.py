"""Single-node baseline trainer — the reference's TF/Keras comparison arm.

Equivalent of ml/experiments/tf_train.py + tflow/{lenet,resnet34}.py: the
reference benchmarks KubeML against a plain single-process TF/Keras run of
the same model; here the baseline is a plain single-process jitted JAX
loop (no K-avg, no masks, no control plane) over the same built-in
models, producing the same result-row schema as the sweep driver so the
two arms are directly comparable.

Usage (synthetic stand-in data, same flag shape as experiments.train):

    python -m experiments.baseline_train --function lenet --epochs 5 \
        --batch 64 --lr 0.01 --out results/lenet-baseline.jsonl
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def train_baseline(function: str, x_train, y_train, x_test, y_test,
                   epochs: int, batch: int, lr: float, seed: int = 0):
    """Plain jitted epoch loop; returns per-epoch rows."""
    import jax
    import jax.numpy as jnp
    import optax

    from kubeml_tpu.models import get_builtin

    model = get_builtin(function)()
    variables = model.init_variables(
        jax.random.PRNGKey(seed), {"x": jnp.asarray(x_train[:batch])})
    # optimizer state persists across the run (conventional single-node
    # training, like the reference's Keras fit); the transform itself is
    # rebuilt from the TRACED epoch inside the step so epoch-stepped LR
    # schedules (e.g. ResNet's decay at epochs 15/25) fire exactly as in
    # the distributed arm. Schedules only scale the update, so the state
    # tree structure is epoch-independent.
    opt_state = model.configure_optimizers(
        jnp.float32(lr), jnp.int32(0)).init(variables["params"])
    ones = jnp.ones((batch,), jnp.float32)

    @jax.jit
    def step(variables, opt_state, xb, yb, key, epoch):
        tx = model.configure_optimizers(jnp.float32(lr), epoch)

        def scalar_loss(params):
            per_ex, new_state = model.loss(
                {**variables, "params": params}, {"x": xb, "y": yb},
                key, ones)
            return per_ex.mean(), new_state
        (loss, new_state), grads = jax.value_and_grad(
            scalar_loss, has_aux=True)(variables["params"])
        updates, opt_state = tx.update(grads, opt_state,
                                       variables["params"])
        params = optax.apply_updates(variables["params"], updates)
        return {**new_state, "params": params}, opt_state, loss

    @jax.jit
    def evaluate(variables, xb, yb):
        m = model.metrics(variables, {"x": xb, "y": yb})
        return {k: v.sum() for k, v in m.items()}

    n = (len(x_train) // batch) * batch
    rows = []
    key = jax.random.PRNGKey(seed + 1)
    for epoch in range(epochs):
        t0 = time.time()
        perm = np.random.RandomState(seed + epoch).permutation(n)
        losses = []
        for i in range(0, n, batch):
            idx = perm[i:i + batch]
            key, sub = jax.random.split(key)
            variables, opt_state, loss = step(
                variables, opt_state, jnp.asarray(x_train[idx]),
                jnp.asarray(y_train[idx]), sub, jnp.int32(epoch))
            losses.append(loss)
        train_loss = float(jnp.stack(losses).mean())  # syncs the epoch
        elapsed = time.time() - t0

        totals, count = {}, 0
        full = (len(x_test) // batch) * batch
        spans = [(i, i + batch) for i in range(0, full, batch)]
        if not spans and len(x_test):
            spans = [(0, len(x_test))]  # tiny test set: one ragged batch
        for lo, hi in spans:
            out = evaluate(variables, jnp.asarray(x_test[lo:hi]),
                           jnp.asarray(y_test[lo:hi]))
            for k, v in out.items():
                totals[k] = totals.get(k, 0.0) + float(v)
            count += hi - lo
        acc = 100.0 * totals.get("accuracy", 0.0) / max(count, 1)
        rows.append({"epoch": epoch + 1, "train_loss": train_loss,
                     "accuracy": acc, "epoch_s": round(elapsed, 4)})
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--function", required=True)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--samples", type=int, default=512,
                    help="synthetic train samples")
    ap.add_argument("--digits", action="store_true",
                    help="train on the REAL digits arm "
                         "(experiments/data.py) instead of synthetic")
    ap.add_argument("--tta", type=float, default=None, metavar="GOAL",
                    help="record time-to-accuracy at GOAL%% (cumulative "
                         "TRAINING seconds until validation accuracy "
                         "first reaches GOAL — the same epoch_duration "
                         "accounting as the engine arm's "
                         "time_to_accuracy, experiments/common/"
                         "experiment.py)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    # same persistent-compile-cache treatment as the engine arm
    # (TrainJob enables it): TTA comparisons must not hand either arm a
    # one-time-per-host compile the other amortizes
    from kubeml_tpu.utils.env import enable_compile_cache
    enable_compile_cache()

    from experiments.train import make_synthetic_split

    rng = np.random.RandomState(0)
    if args.digits:
        from experiments.data import real_digits
        x_train, y_train, x_test, y_test = real_digits()
        dataset = "digits"
    else:
        x_train, y_train = make_synthetic_split(args.function,
                                                args.samples, rng)
        x_test, y_test = make_synthetic_split(args.function,
                                              max(args.samples // 4, 1),
                                              rng)
        dataset = "synthetic"

    t0 = time.time()
    rows = train_baseline(args.function, x_train, y_train, x_test, y_test,
                          args.epochs, args.batch, args.lr)
    wall = time.time() - t0
    epoch_samples = (len(x_train) // args.batch) * args.batch
    mean_epoch_s = float(np.mean([r["epoch_s"] for r in rows]))
    summary = {"function": args.function, "arm": "single-node-baseline",
               "dataset": dataset,
               "epochs": args.epochs, "batch": args.batch, "lr": args.lr,
               "wall_time_s": round(wall, 3),
               "mean_epoch_s": round(mean_epoch_s, 4),
               "samples_per_sec": round(epoch_samples / mean_epoch_s, 1),
               "final_train_loss": rows[-1]["train_loss"],
               "max_accuracy": max(r["accuracy"] for r in rows)}
    if args.tta is not None:
        elapsed, tta = 0.0, None
        for r in rows:
            elapsed += r["epoch_s"]
            if r["accuracy"] >= args.tta:
                tta = round(elapsed, 3)
                break
        summary[f"tta{args.tta:g}_s"] = tta
    print(json.dumps(summary))
    if args.out:
        with open(args.out, "w") as f:
            for r in rows:
                f.write(json.dumps({**summary, **r}) + "\n")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
