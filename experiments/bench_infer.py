"""Concurrent /infer benchmark — the PS serving path under load.

The reference's inference is a vestigial single-shot function invocation
(scheduler/api.go:119-162, live RedisAI tensors, gone at job end). This
framework serves from checkpoints through the PS `/infer` endpoint
(control/ps.py): a ThreadingHTTPServer, a (stamp-keyed) deserialized-
checkpoint LRU, and — round 5 — the InferBatcher, which stacks
concurrent same-shape requests into one device call.

Measured here, all against a REAL ParameterServer over HTTP on this
host's accelerator:

  for k in {1, 4, 16} concurrent clients x N requests each:
      requests/sec, samples/sec, latency p50/p95
  with the micro-batcher ON (default) and OFF (KUBEML_INFER_BATCH=0)

Usage:
    python -m experiments.bench_infer [--out results/infer-bench-v5e.jsonl]
        [--requests 40] [--batch 8]
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time


def _percentile(xs, q):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q / 100.0 * len(xs)))]


def run_server_and_measure(batching: bool, requests: int, batch: int,
                           clients=(1, 4, 16)) -> list:
    import numpy as np

    from kubeml_tpu.control.httpd import http_json
    from kubeml_tpu.control.ps import ParameterServer
    from kubeml_tpu.models import get_builtin
    from kubeml_tpu.train.checkpoint import save_checkpoint

    os.environ["KUBEML_INFER_BATCH"] = "1" if batching else "0"
    import jax

    model = get_builtin("lenet")()
    x0 = np.random.RandomState(0).rand(batch, 28, 28, 1).astype(
        np.float32)
    variables = model.init_variables(jax.random.PRNGKey(0), {"x": x0})
    save_checkpoint("inferbench-lenet", variables,
                    {"model": "lenet", "function": "lenet"})

    ps = ParameterServer(port=0)
    ps.start()
    rows = []
    try:
        url = f"{ps.url}/infer"
        payload = {"model_id": "inferbench-lenet", "data": x0.tolist()}
        http_json("POST", url, payload)  # warm: LRU load + first apply

        for k in clients:
            lat = []
            lat_lock = threading.Lock()
            rng = np.random.RandomState(7)
            bodies = [
                {"model_id": "inferbench-lenet",
                 "data": rng.rand(batch, 28, 28, 1).astype(
                     np.float32).tolist()}
                for _ in range(k)]

            def worker(body):
                mine = []
                for _ in range(requests):
                    t0 = time.perf_counter()
                    out = http_json("POST", url, body)
                    mine.append(time.perf_counter() - t0)
                    assert len(out["predictions"]) == batch
                with lat_lock:
                    lat.extend(mine)

            threads = [threading.Thread(target=worker, args=(b,))
                       for b in bodies]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0
            n = k * requests
            row = {
                "bench": "ps_infer_concurrent",
                "batching": batching, "clients": k,
                "requests": n, "req_batch": batch,
                "requests_per_sec": round(n / elapsed, 1),
                "samples_per_sec": round(n * batch / elapsed, 1),
                "latency_p50_ms": round(_percentile(lat, 50) * 1e3, 2),
                "latency_p95_ms": round(_percentile(lat, 95) * 1e3, 2),
            }
            rows.append(row)
            print(json.dumps(row), flush=True)
    finally:
        ps.stop()
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=40,
                    help="requests per client")
    ap.add_argument("--batch", type=int, default=8,
                    help="samples per request")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    rows = []
    for batching in (False, True):
        rows += run_server_and_measure(batching, args.requests,
                                       args.batch)
    if args.out:
        with open(args.out, "a") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
